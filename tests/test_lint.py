"""dklint self-tests: fixture firing, suppressions, baseline, and the
package-wide gate (distkeras_tpu/ must be clean modulo the committed
baseline).  Pure AST work — no jax import, no devices."""

import json
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.lint

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO_ROOT, "tests", "lint_fixtures")
BASELINE = os.path.join(REPO_ROOT, "tools", "dklint", "baseline.json")
SELFLINT_BASELINE = os.path.join(
    REPO_ROOT, "tools", "dklint", "selflint_baseline.json"
)

sys.path.insert(0, REPO_ROOT)

from tools.dklint import analyze, apply_baseline, load_baseline  # noqa: E402
from tools.dklint.registry import all_rules  # noqa: E402


def _run(fixture, select):
    path = os.path.join(FIXTURES, fixture)
    findings, files = analyze([path], root=REPO_ROOT, select=select)
    return [(f.rule, f.line) for f in findings], files


# --------------------------------------------------------------- per-rule

def test_dk101_host_sync_fixture():
    got, _ = _run("dk101_host_sync.py", ["DK101"])
    assert got == [
        ("DK101", 16),  # .item() in jitted fn
        ("DK101", 17),  # np.asarray in jitted fn
        ("DK101", 18),  # float() on traced arg
        ("DK101", 19),  # jax.device_get
        ("DK101", 25),  # block_until_ready in scan body
        ("DK101", 37),  # .item() in engine hot method
        ("DK101", 52),  # float() on x = x * 2.0 — still param-derived
    ]


def test_dk101_suppression_and_cold_paths():
    got, _ = _run("dk101_host_sync.py", ["DK101"])
    lines = [ln for _, ln in got]
    assert 20 not in lines  # trailing `# dklint: disable=DK101`
    assert 36 not in lines  # float() on a local int, not a traced arg
    assert 40 not in lines  # np.asarray outside any hot path


def test_dk101_v3_provenance_kills_reassignment_fps():
    """The v2 false-positive class: a parameter rebound to a host constant
    (``x = 0.0; float(x)``) and a closure constant synced inside a jitted
    factory product are trace-time constants, not per-step syncs."""
    got, _ = _run("dk101_host_sync.py", ["DK101"])
    lines = [ln for _, ln in got]
    assert 46 not in lines  # float(x) after x = 0.0 rebind
    assert 60 not in lines  # const.item() on an enclosing-factory constant


def test_dk102_recompile_fixture():
    got, _ = _run("dk102_recompile.py", ["DK102"])
    assert got == [
        ("DK102", 8),   # jax.jit(...)(...) immediate invocation
        ("DK102", 18),  # jit construction inside a for loop
        ("DK102", 25),  # traced arg as branch condition
        ("DK102", 34),  # traced arg as range() bound
    ]


def test_dk102_suppression_and_statics():
    got, _ = _run("dk102_recompile.py", ["DK102"])
    lines = [ln for _, ln in got]
    assert 12 not in lines  # suppressed immediate invocation
    assert 27 not in lines  # literal range bound
    assert 52 not in lines  # static_argnums-covered range bound


def test_dk103_donation_fixture():
    got, _ = _run("dk103_donation.py", ["DK103"])
    assert got == [
        ("DK103", 9),   # state.loss read after donating call
        ("DK103", 21),  # read after immediate donate-invocation
    ]


def test_dk103_rebind_and_suppression():
    got, _ = _run("dk103_donation.py", ["DK103"])
    lines = [ln for _, ln in got]
    assert 15 not in lines  # rebound on the call line
    assert 16 not in lines  # use after rebind is the blessed idiom
    assert 27 not in lines  # suppressed


def test_dk104_mesh_axes_fixture():
    got, _ = _run("dk104_mesh_axes.py", ["DK104"])
    assert got == [
        ("DK104", 20),  # psum over typo'd axis
        ("DK104", 21),  # all_gather over unknown axis
        ("DK104", 22),  # axis_index over unknown axis
    ]


def test_dk104_declared_axes_and_suppression():
    got, _ = _run("dk104_mesh_axes.py", ["DK104"])
    lines = [ln for _, ln in got]
    assert 14 not in lines  # *_AXIS constant counts as declared
    assert 15 not in lines  # Mesh(..., ("workers", "seq")) literal counts
    assert 27 not in lines  # suppressed


def test_dk105_locks_fixture():
    got, _ = _run("dk105_locks.py", ["DK105"])
    assert got == [
        ("DK105", 14),  # guarded attr written off-lock
        ("DK105", 22),  # guarded list mutated off-lock
    ]


def test_dk105_exemptions_and_suppression():
    got, _ = _run("dk105_locks.py", ["DK105"])
    lines = [ln for _, ln in got]
    assert 10 not in lines  # __init__ writes exempt
    assert 17 not in lines  # suppressed
    assert 31 not in lines  # attr never touched under the lock
    assert 39 not in lines  # class owns no lock


def test_dk106_wallclock_fixture():
    got, _ = _run("dk106_wallclock.py", ["DK106"])
    assert got == [
        ("DK106", 7),   # deadline = time.time() + timeout
        ("DK106", 8),   # while time.time() < deadline
        ("DK106", 15),  # time.time() - t0
        ("DK106", 19),  # flagged through max(0.0, ...) nesting
    ]


def test_dk106_timestamps_and_suppression():
    got, _ = _run("dk106_wallclock.py", ["DK106"])
    lines = [ln for _, ln in got]
    assert 13 not in lines  # bare t0 = time.time() assignment
    assert 23 not in lines  # suppressed deadline
    assert 29 not in lines  # bare timestamp assignment
    assert 30 not in lines  # timestamp in a dict literal
    assert 36 not in lines  # perf_counter duration is the blessed idiom


def test_dk107_finiteness_fixture():
    got, _ = _run("dk107_finiteness.py", ["DK107"])
    assert got == [
        ("DK107", 11),  # bool(jnp.isnan(...)) in loop body
        ("DK107", 13),  # .item() on a finiteness check per step
        ("DK107", 14),  # np.asarray hostification
        ("DK107", 15),  # jax.device_get hostification
        ("DK107", 20),  # while-test through .any()
        ("DK107", 28),  # if-test through jnp.any reduction
        ("DK107", 35),  # assert syncing every step
    ]


def test_dk107_in_graph_and_suppression():
    got, _ = _run("dk107_finiteness.py", ["DK107"])
    lines = [ln for _, ln in got]
    assert 41 not in lines  # suppressed
    assert 46 not in lines  # jnp.where masking stays on device
    assert 47 not in lines  # summed non-finite counter stays on device
    assert 53 not in lines  # one-off host check outside any loop


def test_dk108_collectives_fixture():
    got, _ = _run("dk108_collectives.py", ["DK108"])
    assert got == [
        ("DK108", 19),  # psum over an axis the shard_map mesh never binds
        ("DK108", 27),  # pmean over 'batch' under pmap(axis_name="devices")
        ("DK108", 69),  # lax.cond branches with different collectives
    ]


def test_dk108_bound_axes_and_suppression():
    got, _ = _run("dk108_collectives.py", ["DK108"])
    lines = [ln for _, ln in got]
    assert 16 not in lines  # axis bound by the shard_map mesh
    assert 35 not in lines  # axis via *_AXIS constant matches vmap axis_name
    assert 42 not in lines  # nested vmap: outer shard_map axes still bound
    assert 53 not in lines  # suppressed
    assert 85 not in lines  # cond with identical collectives per branch


def test_dk109_traced_branch_fixture():
    got, _ = _run("dk109_traced_branch.py", ["DK109"])
    assert got == [
        ("DK109", 8),   # if on traced param of jit-by-name fn
        ("DK109", 14),  # while on traced param 'x'
        ("DK109", 14),  # ... and on traced param 'lo'
        ("DK109", 64),  # if on y = x * 2 — still param-derived
    ]


def test_dk109_exemptions_and_suppression():
    got, _ = _run("dk109_traced_branch.py", ["DK109"])
    lines = [ln for _, ln in got]
    assert 20 not in lines  # `x is None` structure dispatch
    assert 22 not in lines  # .shape comparison is trace-time static
    assert 24 not in lines  # isinstance
    assert 30 not in lines  # static_argnums at the jit call site
    assert 36 not in lines  # suppressed
    assert 43 not in lines  # @jax.jit-decorated fn is DK102's territory
    assert 57 not in lines  # v3: branch on x after x = 0 rebind is host flow


def _run_dk110(tmp_path):
    """DK110 only fires inside the ``distkeras_tpu`` package, so the fixture
    is analyzed from a synthetic package root rather than the checkout."""
    src = open(os.path.join(FIXTURES, "dk110_print_logging.py")).read()
    pkg = tmp_path / "distkeras_tpu"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "hot.py").write_text(src)
    findings, _ = analyze([str(pkg / "hot.py")], root=str(tmp_path),
                          select=["DK110"])
    return [(f.rule, f.line) for f in findings]


def test_dk110_print_logging_fixture(tmp_path):
    assert _run_dk110(tmp_path) == [
        ("DK110", 14),  # print() in a hot module
        ("DK110", 15),  # logging.getLogger(__name__)
        ("DK110", 16),  # from-imported getLogger alias
    ]


def test_dk110_exemptions_and_suppression(tmp_path):
    lines = [ln for _, ln in _run_dk110(tmp_path)]
    assert 22 not in lines  # `emit = print` reference, not a call
    assert 23 not in lines  # suppressed
    assert 28 not in lines  # __main__ guard block is a script entry point


def test_dk110_out_of_package_is_silent():
    # the same source analyzed as tests.lint_fixtures.* is out of scope —
    # tools/ and tests/ keep their CLIs and fixtures
    got, _ = _run("dk110_print_logging.py", ["DK110"])
    assert got == []


def _run_in_package(tmp_path, fixture, select, golden=None):
    """Package-scoped rules (DK111/DK113/DK114) are exercised from a
    synthetic ``distkeras_tpu`` package root, like ``_run_dk110``.  When
    ``golden`` is given it is written to tests/golden/fixture_metrics.txt
    under the same root so DK114 sees it as the exported ground truth."""
    src = open(os.path.join(FIXTURES, fixture)).read()
    pkg = tmp_path / "distkeras_tpu"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "mod.py").write_text(src)
    if golden is not None:
        gd = tmp_path / "tests" / "golden"
        gd.mkdir(parents=True)
        (gd / "fixture_metrics.txt").write_text(golden)
    findings, _ = analyze([str(pkg / "mod.py")], root=str(tmp_path),
                          select=select)
    return [(f.rule, f.line) for f in findings]


def test_dk111_prng_lineage_fixture(tmp_path):
    assert _run_in_package(tmp_path, "dk111_prng_lineage.py", ["DK111"]) == [
        ("DK111", 15),  # second split of the same key (the sampling.py bug)
        ("DK111", 21),  # split then a draw from the already-consumed parent
        ("DK111", 28),  # key consumed in a loop but never advanced there
    ]


def test_dk111_clean_lineages_are_silent(tmp_path):
    lines = [ln for _, ln in
             _run_in_package(tmp_path, "dk111_prng_lineage.py", ["DK111"])]
    assert 35 not in lines and 36 not in lines  # key rebound between draws
    assert 42 not in lines and 43 not in lines  # exclusive if/else arms
    assert 49 not in lines and 50 not in lines  # fold_in + one split coexist
    assert 57 not in lines and 58 not in lines  # key advanced per iteration
    assert 63 not in lines  # vmapped split: not a Name-keyed consumption
    assert 69 not in lines  # inline PRNGKey construction consumed once


def test_dk111_out_of_package_is_silent():
    got, _ = _run("dk111_prng_lineage.py", ["DK111"])
    assert got == []


def test_dk112_blocking_fixture():
    got, _ = _run("dk112_blocking.py", ["DK112"])
    assert got == [
        ("DK112", 17),  # time.sleep in a jitted step
        ("DK112", 22),  # sock.recv in a helper reachable from the jit
        ("DK112", 38),  # untimed queue.get() in the engine decode loop
        ("DK112", 39),  # untimed lock.acquire() in the decode loop
        ("DK112", 43),  # open() in a method the decode loop calls
    ]


def test_dk112_cold_and_timed_calls_are_silent():
    got, _ = _run("dk112_blocking.py", ["DK112"])
    lines = [ln for _, ln in got]
    assert 48 not in lines and 49 not in lines  # cold function: clean
    assert 59 not in lines  # cv.wait(timeout=...) is bounded
    assert 60 not in lines  # queue.get(timeout=...) is bounded
    assert 61 not in lines  # lock.acquire(timeout=...) is bounded
    assert 64 not in lines  # dict.get(key) is not queue.get()


def test_dk112_prefetch_ring_fixture():
    got, _ = _run("dk112_datapipe.py", ["DK112"])
    assert got == [
        ("DK112", 43),  # .item() in the gather path (ring-hot only)
        ("DK112", 44),  # .tolist() in the gather path (ring-hot only)
        ("DK112", 45),  # time.sleep throttling the producer
    ]


def test_dk112_ring_queue_waits_are_silent():
    got, _ = _run("dk112_datapipe.py", ["DK112"])
    lines = [ln for _, ln in got]
    assert 26 not in lines  # q.put(timeout=_TICK) bounded offer
    assert 57 not in lines  # q.get(timeout=_TICK) bounded pull
    assert 66 not in lines  # .item() outside the ring closure: clean


def test_dk112_package_ring_is_clean():
    """The shipped PrefetchRing must satisfy its own rule: bounded waits
    everywhere, no host sync in the producer."""
    path = os.path.join(REPO_ROOT, "distkeras_tpu", "datapipe", "ring.py")
    findings, _ = analyze([path], root=REPO_ROOT, select=["DK112"])
    assert [(f.rule, f.line) for f in findings] == []


def test_dk113_daemon_protocol_fixture(tmp_path):
    assert _run_in_package(
        tmp_path, "dk113_daemon_protocol.py", ["DK113"]
    ) == [
        ("DK113", 20),  # verb 'submit': double reply on one path
        ("DK113", 20),  # dispatch chain has no else leg
        ("DK113", 24),  # verb 'status': replies on some paths only
        ("DK113", 28),  # verb 'drop': never replies
        ("DK113", 34),  # send_data while holding self._cv
        ("DK113", 64),  # endpoint falls off the end
        ("DK113", 70),  # bare return in an endpoint handler
    ]


def test_dk113_disciplined_server_is_silent(tmp_path):
    lines = [ln for _, ln in _run_in_package(
        tmp_path, "dk113_daemon_protocol.py", ["DK113"])]
    # DisciplinedServer (single reply per verb, send after releasing the cv,
    # raise path exempt, else leg present) spans lines 38-60; the
    # disciplined try/except endpoint spans 73-78 — all silent
    assert not any(38 <= ln <= 60 for ln in lines)
    assert not any(73 <= ln <= 78 for ln in lines)


_DK114_GOLDEN = (
    "# HELP serving_widget_latency_seconds latency\n"
    "# TYPE serving_widget_latency_seconds histogram\n"
    "# HELP serving_widgets_total widgets\n"
    "# TYPE serving_widgets_total counter\n"
)


def test_dk114_metric_hygiene_fixture(tmp_path):
    assert _run_in_package(
        tmp_path, "dk114_metric_hygiene.py", ["DK114"], golden=_DK114_GOLDEN
    ) == [
        ("DK114", 16),  # near-miss of golden serving_widgets_total
        ("DK114", 18),  # gauge vs the golden histogram kind
        ("DK114", 25),  # later-site kind conflict with the line-20 gauge
    ]


def test_dk114_clean_registrations_are_silent(tmp_path):
    lines = [ln for _, ln in _run_in_package(
        tmp_path, "dk114_metric_hygiene.py", ["DK114"],
        golden=_DK114_GOLDEN)]
    assert 27 not in lines and 28 not in lines  # idempotent re-registration
    assert 31 not in lines  # exact golden match is ground truth, not a typo
    assert 33 not in lines  # short names never near-miss


def test_dk114_label_disagreement_across_goldens(tmp_path):
    src = (
        "def register(registry):\n"
        '    registry.counter("fixture_rpc_calls_total", help="rpcs")\n'
    )
    pkg = tmp_path / "distkeras_tpu"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "mod.py").write_text(src)
    gd = tmp_path / "tests" / "golden"
    gd.mkdir(parents=True)
    (gd / "a_metrics.txt").write_text(
        "# TYPE fixture_rpc_calls_total counter\n"
        'fixture_rpc_calls_total{run_id="x"} 1\n'
    )
    (gd / "b_metrics.txt").write_text(
        "# TYPE fixture_rpc_calls_total counter\n"
        'fixture_rpc_calls_total{run_id="x",verb="submit"} 1\n'
    )
    findings, _ = analyze([str(pkg / "mod.py")], root=str(tmp_path),
                          select=["DK114"])
    assert len(findings) == 1
    assert "disagree on label keys" in findings[0].message


def test_dk115_socket_timeout_fixture():
    got, _ = _run("dk115_server.py", ["DK115"])
    assert got == [
        ("DK115", 10),  # timeout-less create_connection (call site)
        ("DK115", 30),  # recv on a parameter socket, no settimeout on path
        ("DK115", 34),  # accept on a parameter listener
        ("DK115", 35),  # recv on the accept-derived conn (inherits nothing)
    ]


def test_dk116_retry_cap_fixture():
    got, _ = _run("dk116_retry_daemon.py", ["DK116"])
    assert got == [
        ("DK116", 11),  # hot reconnect: swallowed OSError, no pacing
        ("DK116", 20),  # networking helpers retried forever, unpaced
    ]


def test_dk116_out_of_scope_module_is_silent(tmp_path):
    """The same unbounded retry outside the daemon/server/tier scope stays
    unflagged — a one-shot script may poll however it likes."""
    src = (
        "import socket\n"
        "def f(host):\n"
        "    while True:\n"
        "        try:\n"
        "            return socket.create_connection((host, 1), timeout=1)\n"
        "        except OSError:\n"
        "            pass\n"
    )
    mod = tmp_path / "batch_tool.py"
    mod.write_text(src)
    findings, _ = analyze([str(mod)], root=str(tmp_path), select=["DK116"])
    assert findings == []


def test_dk117_cardinality_fixture(tmp_path):
    assert _run_in_package(
        tmp_path, "dk117_cardinality.py", ["DK117"]
    ) == [
        ("DK117", 11),  # f-string metric name interpolating request_id
        ("DK117", 14),  # % composition with a trace_id variable
        ("DK117", 16),  # .format() with a job_id attribute
        ("DK117", 18),  # labels= dict with a request_id key
        ("DK117", 20),  # labels= dict value reading trace_id
        ("DK117", 22),  # labels= expression reading request_id
    ]


def test_dk117_sanctioned_homes_are_silent(tmp_path):
    """Literal names, bounded-enum families, run_id labels, and trace-span
    args (the sanctioned home for request ids) all stay unflagged."""
    lines = [ln for _, ln in _run_in_package(
        tmp_path, "dk117_cardinality.py", ["DK117"])]
    assert all(ln < 26 for ln in lines), lines  # everything in clean() silent


def test_dk117_out_of_package_is_silent():
    got, _ = _run("dk117_cardinality.py", ["DK117"])
    assert got == []


def test_dk117_tenant_labels_fixture(tmp_path):
    assert _run_in_package(
        tmp_path, "dk117_tenant_labels.py", ["DK117"]
    ) == [
        ("DK117", 17),  # f-string metric name interpolating tenant
        ("DK117", 20),  # % composition with a tenant_id variable
        ("DK117", 22),  # labels= dict with a tenant key
        ("DK117", 24),  # labels= dict value reading tenant_id
        ("DK117", 26),  # labels= expression reading tenant
    ]


def test_dk117_tenant_sanctioned_homes_are_silent(tmp_path):
    """Literal names, bounded deploy labels, span args, and the ledger API
    (the sanctioned aggregation home for tenants) all stay unflagged."""
    lines = [ln for _, ln in _run_in_package(
        tmp_path, "dk117_tenant_labels.py", ["DK117"])]
    assert all(ln < 34 for ln in lines), lines  # everything in clean() silent


def test_dk117_accounting_module_is_tenant_exempt(tmp_path):
    """The bounded top-K ledger module itself may carry tenant state — the
    same source analyzed as distkeras_tpu.telemetry.accounting is clean."""
    src = open(os.path.join(FIXTURES, "dk117_tenant_labels.py")).read()
    pkg = tmp_path / "distkeras_tpu"
    sub = pkg / "telemetry"
    sub.mkdir(parents=True)
    (pkg / "__init__.py").write_text("")
    (sub / "__init__.py").write_text("")
    (sub / "accounting.py").write_text(src)
    findings, _ = analyze([str(sub / "accounting.py")], root=str(tmp_path),
                          select=["DK117"])
    assert findings == []


def test_dk118_atomic_publish_fixture():
    got, _ = _run("dk118_checkpoint_pub.py", ["DK118"])
    assert got == [
        ("DK118", 12),  # json.dump into a bare open(path, "w")
        ("DK118", 17),  # fh = open(...); fh.write(...) with no replace
        ("DK118", 23),  # pickle.dump into open(path, "wb")
        ("DK118", 28),  # open(path, "w").write(...) inline
    ]


def test_dk118_clean_idioms_are_silent():
    """tmp + os.replace / os.rename, read mode, append logs, never-written
    handles, non-literal modes, and the suppression comment all stay
    silent — only in-place publication fires."""
    got, _ = _run("dk118_checkpoint_pub.py", ["DK118"])
    lines = [ln for _, ln in got]
    assert all(ln < 31 for ln in lines), lines


def test_dk118_out_of_scope_module_is_silent(tmp_path):
    """The same bare write outside checkpoint/telemetry/discovery scope is
    fine — private scratch files may be written in place."""
    src = (
        "import json\n"
        "def f(path, obj):\n"
        "    with open(path, 'w') as fh:\n"
        "        json.dump(obj, fh)\n"
    )
    mod = tmp_path / "batch_tool.py"
    mod.write_text(src)
    findings, _ = analyze([str(mod)], root=str(tmp_path), select=["DK118"])
    assert findings == []


def test_dk119_shared_state_race_fixture():
    got, _ = _run("dk119_races.py", ["DK119"])
    assert got == [
        ("DK119", 16),  # unlocked write on the spawned root
        ("DK119", 42),  # unguarded read vs a locked writer
        ("DK119", 52),  # unlocked write on a module global
    ]


def test_dk120_lock_order_fixture():
    got, _ = _run("dk120_lock_order.py", ["DK120"])
    assert got == [
        ("DK120", 12),  # a -> b leg of the direct cycle
        ("DK120", 18),  # b -> a leg of the direct cycle
        ("DK120", 24),  # c -> d through the callee
        ("DK120", 34),  # d -> c closing the interprocedural cycle
    ]


def test_dk121_thread_lifecycle_fixture():
    got, _ = _run("dk121_lifecycle.py", ["DK121"])
    assert got == [
        ("DK121", 7),   # non-daemon thread never joined
        ("DK121", 13),  # runner loop without exception containment
    ]


def test_dk121_joined_and_daemon_threads_are_silent():
    got, _ = _run("dk121_lifecycle.py", ["DK121"])
    lines = [ln for _, ln in got]
    assert 22 not in lines  # joined non-daemon thread
    assert 28 not in lines  # daemon thread
    assert 33 not in lines  # contained runner loop


def test_dk122_unit_hygiene_fixture(tmp_path):
    assert _run_in_package(tmp_path, "dk122_units.py", ["DK122"]) == [
        ("DK122", 18),  # counter without _total
        ("DK122", 19),  # seconds tally is still a counter: needs _total
        ("DK122", 21),  # duration histogram in milliseconds (_ms)
        ("DK122", 22),  # latency token, no unit suffix
        ("DK122", 23),  # _time is not a unit
        ("DK122", 25),  # byte gauge without _bytes
    ]


def test_dk122_canonical_names_are_silent(tmp_path):
    lines = [ln for _, ln in _run_in_package(
        tmp_path, "dk122_units.py", ["DK122"])]
    # register_clean spans lines 29-41: canonical suffixes, unitless gauge,
    # a non-duration histogram, and a computed family are all clean
    assert not any(29 <= ln <= 41 for ln in lines)


def test_dk122_out_of_package_is_silent():
    """Same registrations outside the distkeras_tpu package stay unflagged
    — naming conventions only bind the shipped instrument set."""
    got, _ = _run("dk122_units.py", ["DK122"])
    assert got == []


def test_fixed_modules_stay_concurrency_clean():
    """Regression pins for the in-tree fixes: modules whose DK119/DK120/
    DK121 findings were *fixed* (not baselined) must stay clean when
    analyzed alone, with no baseline applied.  (tier.py and engine.py keep
    justified Event-handoff / internally-locked-queue entries in the main
    baseline and are pinned by the package gate instead.)"""
    for mod in ("distkeras_tpu/fleet.py",
                "distkeras_tpu/telemetry/metrics.py",
                "distkeras_tpu/job_deployment.py"):
        findings, _ = analyze([os.path.join(REPO_ROOT, mod)], root=REPO_ROOT,
                              select=["DK119", "DK120", "DK121"])
        assert findings == [], mod + ":\n" + "\n".join(
            f.render() for f in findings)


def test_concurrency_no_false_positive_corpus():
    """The pinned clean corpus: cv-wait (both sides hold the condition),
    lockwatch maybe_wrap/guard_map state, Event handoff with locked
    accesses, and a handler thread with locked registry access must all
    stay finding-free under every concurrency rule."""
    got, _ = _run("dk119_no_fp.py", ["DK119", "DK120", "DK121"])
    assert got == []


def test_dk115_out_of_scope_module_is_silent(tmp_path):
    """Same code outside the daemon/server scope stays unflagged — batch
    code may legitimately block forever."""
    src = "def f(sock):\n    return sock.recv(16)\n"
    mod = tmp_path / "batch_tool.py"
    mod.write_text(src)
    findings, _ = analyze([str(mod)], root=str(tmp_path), select=["DK115"])
    assert findings == []


# ------------------------------------------------------ interprocedural v2

def test_cross_module_host_sync_found_by_v2():
    """The helper's np.asarray is invisible per-module (v1) but hot once the
    jitted caller in the other file is analyzed alongside it."""
    pair = [os.path.join(FIXTURES, "xmod_engine.py"),
            os.path.join(FIXTURES, "xmod_helper.py")]
    findings, _ = analyze(pair, root=REPO_ROOT, select=["DK101"])
    assert [(f.rule, os.path.basename(f.path), f.line) for f in findings] == [
        ("DK101", "xmod_helper.py", 11),
    ]


def test_cross_module_helper_alone_is_cold():
    findings, _ = analyze(
        [os.path.join(FIXTURES, "xmod_helper.py")],
        root=REPO_ROOT, select=["DK101"],
    )
    assert findings == []


# ------------------------------------------------------------ machinery

def test_file_wide_suppression(tmp_path):
    src = (
        "# dklint: disable=DK102\n"
        "import jax\n"
        "def f(x):\n"
        "    return jax.jit(lambda v: v)(x)\n"
    )
    p = tmp_path / "mod.py"
    p.write_text(src)
    findings, _ = analyze([str(p)], root=str(tmp_path), select=["DK102"])
    assert findings == []


def test_disable_all(tmp_path):
    src = (
        "import jax\n"
        "def f(x):\n"
        "    return jax.jit(lambda v: v)(x)  # dklint: disable=all\n"
    )
    p = tmp_path / "mod.py"
    p.write_text(src)
    findings, _ = analyze([str(p)], root=str(tmp_path), select=["DK102"])
    assert findings == []


def test_decorator_line_suppression_covers_the_def(tmp_path):
    """A trailing directive on a decorator line suppresses findings anywhere
    in the decorated function — previously it only covered the decorator's
    own line, which can never carry the finding."""
    src = (
        "import jax\n"
        "@jax.jit  # dklint: disable=DK101\n"
        "def f(x):\n"
        "    return x.item()\n"
    )
    p = tmp_path / "mod.py"
    p.write_text(src)
    findings, _ = analyze([str(p)], root=str(tmp_path), select=["DK101"])
    assert findings == []


def test_decorator_line_suppression_is_scoped(tmp_path):
    """The decorator-line directive covers only its own function."""
    src = (
        "import jax\n"
        "@jax.jit  # dklint: disable=DK101\n"
        "def f(x):\n"
        "    return x.item()\n"
        "@jax.jit\n"
        "def g(x):\n"
        "    return x.item()\n"
    )
    p = tmp_path / "mod.py"
    p.write_text(src)
    findings, _ = analyze([str(p)], root=str(tmp_path), select=["DK101"])
    assert [(f.rule, f.line) for f in findings] == [("DK101", 7)]


def test_multi_rule_disable(tmp_path):
    src = (
        "import jax\n"
        "@jax.jit  # dklint: disable=DK101,DK102\n"
        "def f(x, n):\n"
        "    if n > 0:\n"
        "        return x.item()\n"
        "    return x\n"
    )
    p = tmp_path / "mod.py"
    p.write_text(src)
    findings, _ = analyze(
        [str(p)], root=str(tmp_path), select=["DK101", "DK102"]
    )
    assert findings == []


def test_baseline_cancels_and_reports_stale(tmp_path):
    src = "import jax\ndef f(x):\n    return jax.jit(lambda v: v)(x)\n"
    p = tmp_path / "mod.py"
    p.write_text(src)
    findings, files = analyze([str(p)], root=str(tmp_path), select=["DK102"])
    assert len(findings) == 1
    entry = {"path": "mod.py", "rule": "DK102",
             "text": "return jax.jit(lambda v: v)(x)", "reason": "test"}
    stale_entry = {"path": "mod.py", "rule": "DK102",
                   "text": "this line no longer exists", "reason": "gone"}
    new, stale = apply_baseline(findings, [entry, stale_entry], files)
    assert new == []
    assert stale == [stale_entry]


def test_all_rules_registered():
    assert sorted(all_rules()) == [
        "DK101", "DK102", "DK103", "DK104", "DK105", "DK106", "DK107",
        "DK108", "DK109", "DK110", "DK111", "DK112", "DK113", "DK114",
        "DK115", "DK116", "DK117", "DK118", "DK119", "DK120", "DK121",
        "DK122", "DK123", "DK124", "DK125", "DK126",
    ]


def test_baseline_entries_have_reasons():
    for path in (BASELINE, SELFLINT_BASELINE):
        entries = load_baseline(path)
        assert entries, f"{path} should not be empty-yet-present"
        for e in entries:
            assert e.get("reason", "").strip(), f"baseline entry lacks a reason: {e}"


# ---------------------------------------------------------------- the gate

def test_package_is_clean_modulo_baseline():
    """The enforced invariant: dklint over distkeras_tpu/ yields zero
    findings that the committed baseline does not account for."""
    pkg = os.path.join(REPO_ROOT, "distkeras_tpu")
    findings, files = analyze([pkg], root=REPO_ROOT)
    new, _stale = apply_baseline(findings, load_baseline(BASELINE), files)
    assert new == [], "new dklint findings:\n" + "\n".join(
        f.render() for f in new
    )


def test_tools_and_tests_clean_modulo_selflint_baseline():
    """The self-lint gate: dklint over its own sources and the test tree
    yields nothing the selflint baseline (deliberate fixture violations)
    does not account for."""
    findings, files = analyze(
        [os.path.join(REPO_ROOT, "tools"), os.path.join(REPO_ROOT, "tests")],
        root=REPO_ROOT,
    )
    new, _stale = apply_baseline(
        findings, load_baseline(SELFLINT_BASELINE), files
    )
    assert new == [], "new self-lint findings:\n" + "\n".join(
        f.render() for f in new
    )


def test_cli_exit_codes():
    env = dict(os.environ, PYTHONPATH=REPO_ROOT)
    ok = subprocess.run(
        [sys.executable, "-m", "tools.dklint", "distkeras_tpu",
         "--root", REPO_ROOT],
        cwd=REPO_ROOT, env=env, capture_output=True, text=True,
    )
    assert ok.returncode == 0, ok.stdout + ok.stderr
    dirty = subprocess.run(
        [sys.executable, "-m", "tools.dklint",
         os.path.join("tests", "lint_fixtures"), "--no-baseline",
         "--root", REPO_ROOT],
        cwd=REPO_ROOT, env=env, capture_output=True, text=True,
    )
    assert dirty.returncode == 1
    assert "DK101" in dirty.stdout


def test_cli_prune_baseline_roundtrip(tmp_path):
    """--prune-baseline drops entries matching nothing and keeps (with
    reasons) the ones still earning their grandfathering."""
    src = "import jax\ndef f(x):\n    return jax.jit(lambda v: v)(x)\n"
    (tmp_path / "mod.py").write_text(src)
    baseline = tmp_path / "baseline.json"
    baseline.write_text(json.dumps({
        "version": 1,
        "findings": [
            {"path": "mod.py", "rule": "DK102",
             "text": "return jax.jit(lambda v: v)(x)", "reason": "live"},
            {"path": "mod.py", "rule": "DK102",
             "text": "this line is long gone", "reason": "stale"},
        ],
    }))
    env = dict(os.environ, PYTHONPATH=REPO_ROOT)
    pruned = subprocess.run(
        [sys.executable, "-m", "tools.dklint", "mod.py",
         "--root", str(tmp_path), "--baseline", str(baseline),
         "--prune-baseline"],
        cwd=tmp_path, env=env, capture_output=True, text=True,
    )
    assert pruned.returncode == 0, pruned.stdout + pruned.stderr
    assert "pruned 1 stale entry, kept 1" in pruned.stdout
    doc = json.loads(baseline.read_text())
    assert [e["reason"] for e in doc["findings"]] == ["live"]
    # round-trip: the pruned baseline still cancels the live finding
    clean = subprocess.run(
        [sys.executable, "-m", "tools.dklint", "mod.py",
         "--root", str(tmp_path), "--baseline", str(baseline)],
        cwd=tmp_path, env=env, capture_output=True, text=True,
    )
    assert clean.returncode == 0, clean.stdout + clean.stderr
    # pruning again is a no-op
    again = subprocess.run(
        [sys.executable, "-m", "tools.dklint", "mod.py",
         "--root", str(tmp_path), "--baseline", str(baseline),
         "--prune-baseline"],
        cwd=tmp_path, env=env, capture_output=True, text=True,
    )
    assert "pruned 0 stale entries, kept 1" in again.stdout


def test_cli_github_format():
    env = dict(os.environ, PYTHONPATH=REPO_ROOT)
    out = subprocess.run(
        [sys.executable, "-m", "tools.dklint",
         os.path.join("tests", "lint_fixtures", "dk104_mesh_axes.py"),
         "--no-baseline", "--root", REPO_ROOT, "--format", "github"],
        cwd=REPO_ROOT, env=env, capture_output=True, text=True,
    )
    assert out.returncode == 1
    lines = [ln for ln in out.stdout.splitlines() if ln]
    assert len(lines) == 3
    for ln in lines:
        assert ln.startswith("::warning file=tests/lint_fixtures/dk104_mesh_axes.py,line=")
        assert "title=dklint DK104::" in ln


def test_cli_json_format():
    env = dict(os.environ, PYTHONPATH=REPO_ROOT)
    out = subprocess.run(
        [sys.executable, "-m", "tools.dklint",
         os.path.join("tests", "lint_fixtures", "dk104_mesh_axes.py"),
         "--no-baseline", "--root", REPO_ROOT, "--format", "json"],
        cwd=REPO_ROOT, env=env, capture_output=True, text=True,
    )
    payload = json.loads(out.stdout)
    assert [f["rule"] for f in payload] == ["DK104"] * 3


def test_cli_sarif_format_roundtrip():
    env = dict(os.environ, PYTHONPATH=REPO_ROOT)
    out = subprocess.run(
        [sys.executable, "-m", "tools.dklint",
         os.path.join("tests", "lint_fixtures", "dk104_mesh_axes.py"),
         "--no-baseline", "--root", REPO_ROOT, "--format", "sarif"],
        cwd=REPO_ROOT, env=env, capture_output=True, text=True,
    )
    assert out.returncode == 1
    doc = json.loads(out.stdout)
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "dklint"
    # the schema requires informationUri, when present, to be an absolute
    # URI — a repo-relative path breaks strict consumers
    info = run["tool"]["driver"].get("informationUri")
    assert info is None or "://" in info
    # every registered rule is described even though only DK104 fired
    rule_ids = [r["id"] for r in run["tool"]["driver"]["rules"]]
    assert rule_ids == sorted(all_rules())
    results = run["results"]
    assert [r["ruleId"] for r in results] == ["DK104"] * 3
    for r in results:
        loc = r["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uri"] == \
            "tests/lint_fixtures/dk104_mesh_axes.py"
        assert loc["region"]["startLine"] > 0
        assert loc["region"]["startColumn"] > 0  # SARIF columns are 1-based
        assert r["message"]["text"]


def _git(cwd, *args):
    return subprocess.run(
        ["git", "-c", "user.email=t@t", "-c", "user.name=t", *args],
        cwd=cwd, capture_output=True, text=True, check=True,
    )


def test_cli_since_filters_to_changed_files(tmp_path):
    """--since reports only findings in files changed vs. the ref, while
    still analyzing the whole tree (so cross-module facts stay correct)."""
    _git(tmp_path, "init", "-q")
    old = tmp_path / "old.py"
    old.write_text(
        "import jax\ndef f(x):\n    return jax.jit(lambda v: v)(x)\n"
    )
    _git(tmp_path, "add", "-A")
    _git(tmp_path, "commit", "-qm", "seed")
    new = tmp_path / "new.py"
    new.write_text(
        "import jax\ndef g(x):\n    return jax.jit(lambda v: v)(x)\n"
    )
    env = dict(os.environ, PYTHONPATH=REPO_ROOT)
    out = subprocess.run(
        [sys.executable, "-m", "tools.dklint", ".", "--no-baseline",
         "--root", str(tmp_path), "--since", "HEAD", "--format", "json"],
        cwd=tmp_path, env=env, capture_output=True, text=True,
    )
    assert out.returncode == 1, out.stdout + out.stderr
    payload = json.loads(out.stdout)
    # old.py's finding pre-dates the ref and is filtered; untracked new.py
    # counts as changed
    assert [(f["path"], f["rule"]) for f in payload] == [("new.py", "DK102")]
    # with everything committed, the diff set is empty -> clean exit
    _git(tmp_path, "add", "-A")
    _git(tmp_path, "commit", "-qm", "more")
    clean = subprocess.run(
        [sys.executable, "-m", "tools.dklint", ".", "--no-baseline",
         "--root", str(tmp_path), "--since", "HEAD"],
        cwd=tmp_path, env=env, capture_output=True, text=True,
    )
    assert clean.returncode == 0, clean.stdout + clean.stderr


def test_cli_since_with_root_below_git_toplevel(tmp_path):
    """`git diff` paths are cwd-relative (--relative), so a --root that is
    a subdirectory of the git toplevel still matches root-relative
    findings instead of silently filtering everything."""
    _git(tmp_path, "init", "-q")
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    mod = pkg / "mod.py"
    mod.write_text("x = 1\n")
    _git(tmp_path, "add", "-A")
    _git(tmp_path, "commit", "-qm", "seed")
    mod.write_text(
        "import jax\ndef g(x):\n    return jax.jit(lambda v: v)(x)\n"
    )
    env = dict(os.environ, PYTHONPATH=REPO_ROOT)
    out = subprocess.run(
        [sys.executable, "-m", "tools.dklint", ".", "--no-baseline",
         "--root", str(pkg), "--since", "HEAD", "--format", "json"],
        cwd=pkg, env=env, capture_output=True, text=True,
    )
    assert out.returncode == 1, out.stdout + out.stderr
    payload = json.loads(out.stdout)
    assert [(f["path"], f["rule"]) for f in payload] == [("mod.py", "DK102")]


def test_cli_since_follows_renames(tmp_path):
    """A file renamed since the ref must lint under its *new* path — the
    pre-rename diff leg dropped renamed files silently (no R-row parsing)."""
    _git(tmp_path, "init", "-q")
    old = tmp_path / "old_name.py"
    old.write_text(
        "import jax\ndef f(x):\n    return jax.jit(lambda v: v)(x)\n"
    )
    _git(tmp_path, "add", "-A")
    _git(tmp_path, "commit", "-qm", "seed")
    _git(tmp_path, "mv", "old_name.py", "new_name.py")
    env = dict(os.environ, PYTHONPATH=REPO_ROOT)
    out = subprocess.run(
        [sys.executable, "-m", "tools.dklint", ".", "--no-baseline",
         "--root", str(tmp_path), "--since", "HEAD", "--format", "json"],
        cwd=tmp_path, env=env, capture_output=True, text=True,
    )
    assert out.returncode == 1, out.stdout + out.stderr
    payload = json.loads(out.stdout)
    assert [(f["path"], f["rule"]) for f in payload] == [
        ("new_name.py", "DK102")
    ]


def test_changed_files_reports_both_sides_of_a_rename(tmp_path):
    from tools.dklint.cli import changed_files

    _git(tmp_path, "init", "-q")
    (tmp_path / "a.py").write_text("x = 1\n")
    _git(tmp_path, "add", "-A")
    _git(tmp_path, "commit", "-qm", "seed")
    _git(tmp_path, "mv", "a.py", "b.py")
    changed = changed_files(str(tmp_path), "HEAD")
    assert {"a.py", "b.py"} <= changed


def test_analyze_jobs_matches_sequential():
    """--jobs fan-out must be invisible in the output: identical findings,
    identical order."""
    seq, _ = analyze([FIXTURES], root=REPO_ROOT)
    par, _ = analyze([FIXTURES], root=REPO_ROOT, jobs=2)
    assert par == seq
    assert seq  # non-vacuous: the fixture tree fires plenty


def test_cli_since_bad_ref_is_usage_error(tmp_path):
    _git(tmp_path, "init", "-q")
    (tmp_path / "mod.py").write_text("x = 1\n")
    env = dict(os.environ, PYTHONPATH=REPO_ROOT)
    out = subprocess.run(
        [sys.executable, "-m", "tools.dklint", ".", "--no-baseline",
         "--root", str(tmp_path), "--since", "no-such-ref"],
        cwd=tmp_path, env=env, capture_output=True, text=True,
    )
    assert out.returncode == 2
    assert "--since" in out.stderr


# ------------------------------------------------- DK123–DK126 shape rules

def test_dk123_shard_spec_fixture():
    got, _ = _run("dk123_shard_specs.py", ["DK123"])
    assert got == [
        ("DK123", 16),  # wrong-rank in_specs vs rank-2 operand
        ("DK123", 20),  # axis absent from governing mesh
        ("DK123", 26),  # duplicate axis in one PartitionSpec
        ("DK123", 42),  # dp=2 provably does not divide 7
        ("DK123", 48),  # 3 in_specs entries, 2 operands
    ]


def test_dk123_no_fp_and_suppression():
    got, _ = _run("dk123_shard_specs.py", ["DK123"])
    lines = [ln for _, ln in got]
    assert 35 not in lines  # sound specs: dp|6, tp|16
    assert 56 not in lines  # single-spec pytree prefix is legal
    assert 62 not in lines  # trailing disable directive
    assert 63 not in lines


def test_dk123_compat_partial_manual_fixture():
    """The jax<0.5 shim's NotImplementedError, statically (satellite: the
    pipeline x tensor-parallel composition documented in CHANGES PR 1)."""
    got, _ = _run("dk123_compat_partial.py", ["DK123"])
    assert got == [
        ("DK123", 14),  # axis_names strict subset of mesh axes
        ("DK123", 37),  # compat path runs the same axis checks as direct
        ("DK123", 44),  # ... including through an import alias
    ]


def test_dk123_nested_mapper_shadowed_axis():
    """shard_map under vmap with a shadowed axis name: the vmap binding
    must not confuse the mesh judgement in either direction, and
    compat.shard_map resolves to the same judgement as direct shard_map."""
    got, _ = _run("dk123_nested_mappers.py", ["DK123"])
    assert got == [
        ("DK123", 35),  # bad spec is still flagged under the shadow
        ("DK123", 48),  # direct shard_map: wrong-rank
        ("DK123", 48),  # compat.shard_map: same finding, same line
    ]
    # the sound nested case (vmap axis_name == mesh axis) stays silent
    assert all(ln > 30 for _, ln in got)


def test_dk123_nested_mapper_dk108_interplay():
    """DK108 must still accept the collective inside the nested mapper —
    the axis is bound by both the mesh and the vmap."""
    got, _ = _run("dk123_nested_mappers.py", ["DK108"])
    assert got == []


def test_dk124_collective_shapes_fixture():
    got, _ = _run("dk124_collective_shapes.py", ["DK124"])
    assert got == [
        ("DK124", 14),  # all_gather dim index out of range
        ("DK124", 19),  # psum_scatter dim index out of range
        ("DK124", 24),  # axis size 4 does not divide scattered dim 6
        ("DK124", 28),  # ppermute duplicate source
        ("DK124", 32),  # ppermute index outside axis size
    ]


def test_dk124_no_fp_and_suppression():
    got, _ = _run("dk124_collective_shapes.py", ["DK124"])
    lines = [ln for _, ln in got]
    for good_line in (37, 38, 39, 40, 41, 46):
        assert good_line not in lines


def test_dk124_same_module_axis_size_conflict(tmp_path):
    """Two literal mesh constructions sizing the same axis differently in
    one (non-test) module is the cross-engine size-conflict smell."""
    mod = tmp_path / "sizes.py"
    mod.write_text(
        "import jax\n"
        "import numpy as np\n"
        "from jax.sharding import Mesh\n"
        "\n"
        "A = Mesh(np.array(jax.devices()).reshape(4, 2), ('dp', 'tp'))\n"
        "B = Mesh(np.array(jax.devices()).reshape(2, 4), ('dp', 'tp'))\n"
    )
    findings, _ = analyze([str(mod)], root=str(tmp_path), select=["DK124"])
    assert [(f.rule, f.line) for f in findings] == [
        ("DK124", 5),  # anchored on the first construction of the axis
        ("DK124", 5),  # once per conflicted axis (dp and tp)
    ]


def test_dk125_pallas_fixture():
    got, _ = _run("dk125_pallas.py", ["DK125"])
    assert got == [
        ("DK125", 17),  # kernel stores float16, out_shape says float32
        ("DK125", 22),  # in_specs block does not divide dim
        ("DK125", 22),  # ... and out_specs likewise
        ("DK125", 33),  # grid x block covers 64 of 128 (in_specs)
        ("DK125", 33),  # ... and out_specs likewise
        ("DK125", 44),  # kernel arity vs in+out+scratch refs
        ("DK125", 55),  # out_specs / out_shape pairing
        ("DK125", 67),  # block rank vs array rank
    ]


def test_dk125_no_fp():
    got, _ = _run("dk125_pallas.py", ["DK125"])
    lines = [ln for _, ln in got]
    # the flash-attention-style sound call and the symbolic one stay silent
    assert all(ln <= 67 for ln in lines), lines


def test_dk126_sharding_drift_fixture():
    got, _ = _run("dk126_sharding_drift.py", ["DK126"])
    assert got == [
        ("DK126", 16),  # device_put P('dp') into shard_map P(None,'tp')
        ("DK126", 22),  # with_sharding_constraint P('tp') into P('dp')
        ("DK126", 41),  # jit in_shardings drift
    ]


def test_dk126_no_fp_and_suppression():
    got, _ = _run("dk126_sharding_drift.py", ["DK126"])
    lines = [ln for _, ln in got]
    assert 30 not in lines  # same axis set: no drift
    assert 36 not in lines  # replicated producer entering a mesh is normal
    assert 47 not in lines  # trailing disable directive


def test_shapes_report_cli():
    """--shapes-report emits the per-engine layout table: engine buckets,
    shard_map rows with resolved specs, deterministic output."""
    env = dict(os.environ, PYTHONPATH=REPO_ROOT)
    out = subprocess.run(
        [sys.executable, "-m", "tools.dklint", "distkeras_tpu",
         "--root", REPO_ROOT, "--shapes-report"],
        cwd=REPO_ROOT, env=env, capture_output=True, text=True,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    assert "dkshape layout report" in out.stdout
    for bucket in ("engine", "gspmd", "pipeline", "serving"):
        assert f"==== {bucket} ====" in out.stdout
    assert "shard_map[compat]" in out.stdout
    assert "pallas_call" in out.stdout
    # deterministic: a second run is byte-identical (report is an artifact)
    again = subprocess.run(
        [sys.executable, "-m", "tools.dklint", "distkeras_tpu",
         "--root", REPO_ROOT, "--shapes-report"],
        cwd=REPO_ROOT, env=env, capture_output=True, text=True,
    )
    assert again.stdout == out.stdout


def test_cli_stale_warning_in_every_format_and_select_scoped(tmp_path):
    """CI greps the --format github legs for "stale baseline entry", so
    the warning must reach stderr in non-text formats too; a --select
    run must NOT call other rules' entries stale (it produced no
    findings for them, so their staleness is undecidable)."""
    src = "import jax\ndef f(x):\n    return jax.jit(lambda v: v)(x)\n"
    (tmp_path / "mod.py").write_text(src)
    baseline = tmp_path / "baseline.json"
    baseline.write_text(json.dumps({
        "version": 1,
        "findings": [
            {"path": "mod.py", "rule": "DK102",
             "text": "this line is long gone", "reason": "stale"},
        ],
    }))
    env = dict(os.environ, PYTHONPATH=REPO_ROOT)

    def run(*extra):
        return subprocess.run(
            [sys.executable, "-m", "tools.dklint", "mod.py",
             "--root", str(tmp_path), "--baseline", str(baseline), *extra],
            cwd=tmp_path, env=env, capture_output=True, text=True,
        )

    for fmt in ("github", "sarif", "json", "text"):
        got = run("--format", fmt)
        assert "stale baseline entry" in got.stderr, (fmt, got.stderr)
    # DK101 selected: the DK102 entry's staleness is out of scope
    scoped = run("--select", "DK101")
    assert "stale baseline entry" not in scoped.stderr, scoped.stderr
    # ...but a select that covers the entry's rule still reports it
    covered = run("--select", "DK102")
    assert "stale baseline entry" in covered.stderr, covered.stderr
