"""Distributed inference (VERDICT r1 item 2): ModelPredictor must actually
shard batches over the device mesh — per-device shards on the 8-CPU mesh,
outputs equal to the single-device path — and a bare flax module without
params must lazily initialise from real data (conv input shapes included)."""

import numpy as np

import jax

from distkeras_tpu import frame
from distkeras_tpu.models import CIFARCNN, MLP, FlaxModel
from distkeras_tpu.predictors import ModelPredictor


def _digits_df(n=640, d=16):
    rng = np.random.default_rng(0)
    return frame.from_numpy(rng.normal(size=(n, d)).astype(np.float32))


def _trained_mlp(d=16):
    adapter = FlaxModel(MLP(features=(32,), num_classes=4))
    params, state = adapter.init(jax.random.key(0), np.zeros((2, d), np.float32))
    return adapter, params, state


def test_distributed_predict_matches_single_device():
    adapter, params, state = _trained_mlp()
    df = _digits_df(n=640)
    dist = ModelPredictor(adapter, params=params, state=state,
                          batch_size=64, distribute_threshold=1)
    single = ModelPredictor(adapter, params=params, state=state,
                            batch_size=64, num_devices=1)
    out_d = dist.predict(df).column("prediction")
    out_s = single.predict(df).column("prediction")
    assert dist.last_mode == "distributed" and dist.n_dev == jax.device_count()
    assert single.last_mode == "single"
    np.testing.assert_allclose(np.stack(out_d), np.stack(out_s), rtol=1e-5, atol=1e-6)


def test_batches_are_sharded_per_device():
    adapter, params, state = _trained_mlp()
    p = ModelPredictor(adapter, params=params, state=state, batch_size=8)
    chunk = np.zeros((8 * p.n_dev, 16), np.float32)
    sharded = p._shard_batch(chunk)
    shards = sharded.addressable_shards
    assert len(shards) == p.n_dev == jax.device_count()
    assert len({s.device for s in shards}) == p.n_dev
    assert all(s.data.shape[0] == 8 for s in shards)


def test_small_frames_fall_back_to_single_device():
    adapter, params, state = _trained_mlp()
    p = ModelPredictor(adapter, params=params, state=state,
                       batch_size=64, distribute_threshold=64)
    p.predict(_digits_df(n=16))
    assert p.last_mode == "single"


def test_uneven_tail_batch_is_exact():
    adapter, params, state = _trained_mlp()
    n = 8 * 64 + 13  # forces a padded tail global batch
    df = _digits_df(n=n)
    dist = ModelPredictor(adapter, params=params, state=state,
                          batch_size=64, distribute_threshold=1)
    single = ModelPredictor(adapter, params=params, state=state,
                            batch_size=64, num_devices=1)
    out_d = np.stack(dist.predict(df).column("prediction"))
    out_s = np.stack(single.predict(df).column("prediction"))
    assert len(out_d) == n
    np.testing.assert_allclose(out_d, out_s, rtol=1e-5, atol=1e-6)


def test_lazy_init_from_real_batch_handles_conv_models():
    # Round-1 bug: bare flax module without params init'd with zeros((1, 1)),
    # which throws for conv models.  Now init comes from the first real batch.
    rng = np.random.default_rng(1)
    imgs = rng.normal(size=(32, 32, 32, 3)).astype(np.float32)
    df = frame.from_numpy(imgs)
    p = ModelPredictor(FlaxModel(CIFARCNN()), batch_size=16)
    out = p.predict(df).column("prediction")
    assert np.stack(out).shape == (32, 10)
    np.testing.assert_allclose(np.stack(out).sum(axis=-1), 1.0, rtol=1e-4)
