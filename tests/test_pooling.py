"""ops.pooling: the reshape fast path must match flax.linen.max_pool exactly
(forward AND gradient), and the fallback must engage for overlapping /
padded / ragged cases."""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu.ops.pooling import max_pool


def _grad_of(pool_fn, x, **kw):
    return jax.grad(lambda a: jnp.sum(pool_fn(a, **kw) ** 2))(x)


@pytest.mark.parametrize(
    "shape,window,strides",
    [
        ((4, 8, 8, 3), (2, 2), (2, 2)),      # fast path, NHWC
        ((4, 8, 8, 3), (2, 2), None),         # strides default to window
        ((2, 12, 6, 5), (3, 2), (3, 2)),      # non-square fast path
        ((3, 10, 7), (2,), (2,)),             # NWC 1-D fast path (seq models)
        ((4, 8, 8, 3), (2, 2), (1, 1)),       # overlapping -> fallback
        ((4, 7, 7, 3), (2, 2), (2, 2)),       # ragged dims -> fallback
    ],
)
def test_matches_flax(shape, window, strides):
    x = jnp.asarray(np.random.default_rng(0).normal(size=shape), jnp.float32)
    kw = dict(window_shape=window, strides=strides)
    ref_kw = dict(window_shape=window, strides=strides or window)
    np.testing.assert_allclose(max_pool(x, **kw), nn.max_pool(x, **ref_kw))
    np.testing.assert_allclose(
        _grad_of(max_pool, x, **kw), _grad_of(nn.max_pool, x, **ref_kw)
    )


def test_same_padding_falls_back():
    x = jnp.asarray(np.random.default_rng(1).normal(size=(2, 8, 8, 4)), jnp.float32)
    got = max_pool(x, (2, 2), strides=(2, 2), padding="SAME")
    ref = nn.max_pool(x, (2, 2), strides=(2, 2), padding="SAME")
    np.testing.assert_allclose(got, ref)


def test_jit_and_dtype_preserved():
    x = jnp.asarray(np.random.default_rng(2).normal(size=(2, 4, 4, 8)), jnp.bfloat16)
    out = jax.jit(max_pool)(x)  # dklint: disable=DK102 — one-shot test
    assert out.dtype == jnp.bfloat16
    assert out.shape == (2, 2, 2, 8)
