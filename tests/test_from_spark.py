"""from_spark ingestion bridge (VERDICT r1 item 9, SURVEY §7 design stance:
DataFrame facade "with an optional pyspark adapter").

pyspark isn't installed in CI, so the adapter's logic is exercised against a
duck-typed stand-in implementing the same surface (columns / toPandas /
collect / rdd.getNumPartitions, with Spark-ML-style vector values exposing
toArray); a real-pyspark round-trip runs when pyspark is importable."""

import numpy as np
import pytest

import distkeras_tpu as dk
from distkeras_tpu.frame import from_spark


class _FakeVector:
    """Duck-type of pyspark.ml.linalg.DenseVector."""

    def __init__(self, values):
        self._values = list(values)

    def toArray(self):
        return np.asarray(self._values)


class _FakeRDD:
    def __init__(self, n):
        self._n = n

    def getNumPartitions(self):
        return self._n


class _FakeSparkDF:
    """Duck-type of the pyspark.sql.DataFrame surface from_spark touches."""

    def __init__(self, rows, partitions=3, pandas_ok=True):
        self._rows = rows
        self.columns = list(rows[0].keys())
        self.rdd = _FakeRDD(partitions)
        self._pandas_ok = pandas_ok

    def toPandas(self):
        if not self._pandas_ok:
            raise RuntimeError("Arrow unavailable")
        import pandas as pd

        return pd.DataFrame(self._rows)

    def collect(self):
        return self._rows


def _rows(n=6):
    return [
        {"features": _FakeVector([float(i), float(i) + 0.5]),
         "label": i % 2,
         "name": f"row{i}"}
        for i in range(n)
    ]


@pytest.mark.parametrize("pandas_ok", [True, False])  # Arrow path and collect fallback
def test_from_spark_densifies_vectors(pandas_ok):
    df = from_spark(_FakeSparkDF(_rows(), pandas_ok=pandas_ok))
    assert df.columns == ["features", "label", "name"]
    assert len(df) == 6
    assert df.num_partitions == 3
    feats = df.matrix("features")
    np.testing.assert_allclose(feats[:, 1] - feats[:, 0], 0.5)
    assert list(df.column("label")) == [0, 1, 0, 1, 0, 1]


def test_from_spark_column_subset():
    df = from_spark(_FakeSparkDF(_rows()), columns=["features", "label"])
    assert df.columns == ["features", "label"]


def test_from_spark_feeds_training():
    df = from_spark(_FakeSparkDF(_rows(64)))
    df = dk.OneHotTransformer(2, input_col="label",
                              output_col="label_encoded").transform(df)
    from distkeras_tpu.models import MLP, FlaxModel

    t = dk.SingleTrainer(FlaxModel(MLP(features=(8,), num_classes=2)),
                         loss="categorical_crossentropy",
                         worker_optimizer=("sgd", {"learning_rate": 0.05}),
                         features_col="features", label_col="label_encoded",
                         batch_size=8, num_epoch=1)
    trained = t.train(df)
    assert trained.predict(df.matrix("features")).shape == (64, 2)


class _FakeSparkSession:
    """Duck-type of the SparkSession surface to_spark touches."""

    def __init__(self):
        self.received = None

    def createDataFrame(self, data):
        self.received = data
        return ("spark-df", data)


def test_to_spark_full_round_trip():
    """from_spark -> transform -> train -> predict -> to_spark: the egress
    boundary closes the reference's in-Spark pipeline loop (VERDICT r2
    missing item 3)."""
    df = from_spark(_FakeSparkDF(_rows(64)))
    df = dk.OneHotTransformer(2, input_col="label",
                              output_col="label_encoded").transform(df)
    from distkeras_tpu.models import MLP, FlaxModel

    t = dk.SingleTrainer(FlaxModel(MLP(features=(8,), num_classes=2)),
                         loss="categorical_crossentropy",
                         worker_optimizer=("sgd", {"learning_rate": 0.05}),
                         features_col="features", label_col="label_encoded",
                         batch_size=8, num_epoch=1)
    trained = t.train(df)
    pred = dk.ModelPredictor(trained, features_col="features").predict(df)

    spark = _FakeSparkSession()
    out, received = dk.to_spark(pred, spark, columns=["features", "label", "prediction"])
    assert out == "spark-df"
    # pandas path: vector columns became per-row float lists (array<double>)
    assert list(received.columns) == ["features", "label", "prediction"]
    assert len(received) == 64
    first_pred = received["prediction"][0]
    assert isinstance(first_pred, list) and len(first_pred) == 2
    assert all(isinstance(v, float) for v in first_pred)
    np.testing.assert_allclose(received["features"][0],
                               np.asarray(df.column("features")[0], float))
    # scalar column passes through untouched
    assert received["label"].tolist() == [i % 2 for i in range(64)]


def test_to_spark_rows_fallback_without_pandas(monkeypatch):
    import builtins

    real_import = builtins.__import__

    def no_pandas(name, *a, **k):
        if name == "pandas":
            raise ImportError(name)
        return real_import(name, *a, **k)

    monkeypatch.setattr(builtins, "__import__", no_pandas)
    df = from_spark(_FakeSparkDF(_rows(4)))
    spark = _FakeSparkSession()
    _, received = dk.to_spark(df, spark, columns=["features", "label"])
    assert isinstance(received, list) and len(received) == 4
    assert set(received[0]) == {"features", "label"}
    assert received[0]["features"] == [0.0, 0.5]


def test_from_spark_real_pyspark_roundtrip():
    pyspark = pytest.importorskip("pyspark")
    from pyspark.ml.linalg import Vectors
    from pyspark.sql import SparkSession

    spark = SparkSession.builder.master("local[1]").getOrCreate()
    try:
        sdf = spark.createDataFrame(
            [(Vectors.dense([1.0, 2.0]), 0), (Vectors.dense([3.0, 4.0]), 1)],
            ["features", "label"],
        )
        df = from_spark(sdf)
        np.testing.assert_allclose(df.matrix("features"),
                                   [[1.0, 2.0], [3.0, 4.0]])
        assert list(df.column("label")) == [0, 1]
    finally:
        spark.stop()


def test_to_spark_passes_string_columns_through():
    """Egress must not force-cast non-numeric object columns: a Spark frame
    routinely carries string columns (ids, raw text) alongside the numeric
    ones, and astype(float) on them raised ValueError — the round trip
    failed on exactly the frames Spark users actually have."""
    df = from_spark(_FakeSparkDF(_rows(8)))
    df = df.with_column("doc_id", np.array([f"doc-{i}" for i in range(8)],
                                           dtype=object))
    spark = _FakeSparkSession()
    _, received = dk.to_spark(df, spark, columns=["features", "doc_id"])
    assert received["doc_id"].tolist() == [f"doc-{i}" for i in range(8)]
    feats = received["features"][0]
    assert isinstance(feats, list) and all(isinstance(v, float) for v in feats)
