"""Runtime sanitizer tests (DISTKERAS_SANITIZE): mode resolution and the
cached-bool convention, the zero-cost pin for the disabled path
(byte-identical lowered programs), and one seeded violation per guard
proving each catches its dklint twin's target — an in-loop ``.item()``
trips the transfer guard (DK101), donated-but-live buffers are poisoned
(DK103), and off-lock mutation/inversion trips the lock watchdog (DK105).
"""

import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import distkeras_tpu as dk
from distkeras_tpu import sanitizer, telemetry
from distkeras_tpu.algorithms import Downpour
from distkeras_tpu.data import epoch_arrays
from distkeras_tpu.frame import from_numpy
from distkeras_tpu.job_deployment import PunchcardServer
from distkeras_tpu.models import MLP, FlaxModel
from distkeras_tpu.parallel.engine import WindowedEngine
from distkeras_tpu.sanitizer import donation, lockwatch, runtime, transfer
from distkeras_tpu.sanitizer.lockwatch import LockOrderViolation
from distkeras_tpu.sanitizer.transfer import TransferViolation


@pytest.fixture(autouse=True)
def reset_sanitizer():
    """Sanitizer mode is process-cached (engines read it at build); leave
    every test with env-driven defaults and empty watchdog state."""
    yield
    sanitizer.configure(None)
    lockwatch.reset()
    donation.reset_stats()
    telemetry.configure(None)
    telemetry.trace.reset()
    telemetry.metrics.reset()


def _toy(n=128, d=8, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d)).astype(np.float32)
    w = rng.normal(size=(d,))
    y = (x @ w > 0).astype(np.int32)
    onehot = np.zeros((n, 2), np.float32)
    onehot[np.arange(n), y] = 1.0
    return x, onehot


def _mlp():
    return FlaxModel(MLP(features=(16,), num_classes=2))


def _engine(**kw):
    return WindowedEngine(
        _mlp(),
        loss=kw.pop("loss", "categorical_crossentropy"),
        worker_optimizer=("sgd", {"learning_rate": 0.1}),
        rule=Downpour(communication_window=2),
        num_workers=2,
        **kw,
    )


def _epoch_data(eng, x, onehot, batch=16, window=2):
    state = eng.init_state(jax.random.PRNGKey(0), x[:batch])
    xs, ys = epoch_arrays(x, onehot, eng.num_workers, batch, window)
    xs, ys = eng.shard_batches(xs, ys)
    return state, xs, ys


def _leaky_loss():
    """A loss with a deliberate in-loop host sync — the seeded violation
    DK101 flags statically and the transfer guard must catch at runtime."""
    const = jnp.asarray(2.0)

    def loss(out, y):
        scale = const.item()  # closure constant: trace-time sync, legal under v3 provenance
        return jnp.mean((out - y) ** 2) * scale

    return loss


# ------------------------------------------------------------ mode switch

def test_mode_resolution_from_env(monkeypatch):
    for raw, expect in [("", "off"), ("0", "off"), ("false", "off"),
                        ("no", "off"), ("1", "record"), ("true", "record"),
                        ("record", "record"), ("strict", "strict")]:
        sanitizer.configure(None)
        monkeypatch.setenv("DISTKERAS_SANITIZE", raw)
        assert sanitizer.mode() == expect, raw
    sanitizer.configure(None)
    monkeypatch.delenv("DISTKERAS_SANITIZE", raising=False)
    assert (sanitizer.mode(), sanitizer.enabled(), sanitizer.strict()) == (
        "off", False, False)


def test_mode_is_cached_until_reconfigured(monkeypatch):
    """The cached-bool convention: after the first read the env var is never
    consulted again, so the engines' build-time snapshot stays coherent."""
    sanitizer.configure(None)
    monkeypatch.delenv("DISTKERAS_SANITIZE", raising=False)
    assert sanitizer.mode() == "off"
    monkeypatch.setenv("DISTKERAS_SANITIZE", "strict")
    assert sanitizer.mode() == "off"  # cached
    sanitizer.configure(None)  # explicit reset re-reads
    assert sanitizer.mode() == "strict"


def test_configure_rejects_unknown_mode():
    with pytest.raises(ValueError, match="mode must be one of"):
        sanitizer.configure("paranoid")


# ----------------------------------------------------- transfer guard unit

def test_transfer_guard_strict_raises_and_names_label():
    sanitizer.configure("strict")
    const = jnp.asarray(2.0)
    x = jnp.ones(3)  # created outside the guard, like shard_batches output

    @jax.jit
    def f(a):
        return a * const.item()  # closure constant: trace-time sync, legal under v3 provenance

    with pytest.raises(TransferViolation, match="guard 'unit_label'"):
        with transfer.guard("unit_label"):
            f(x)


def test_transfer_guard_clean_program_passes_strict():
    sanitizer.configure("strict")
    x = jnp.ones(8)

    @jax.jit
    def f(a):
        return jnp.sum(a * 3.0) + jnp.arange(a.shape[0]).sum()

    with transfer.guard("clean"):
        out = f(x)  # trace + compile + execute all inside the guard
    assert float(jax.block_until_ready(out)) == pytest.approx(52.0)


def test_transfer_guard_record_counts_and_continues():
    sanitizer.configure("record")
    telemetry.metrics.reset()
    const = jnp.asarray(2.0)
    x = jnp.ones(3)

    @jax.jit
    def f(a):
        return a * const.item()  # closure constant: trace-time sync, legal under v3 provenance

    with pytest.warns(RuntimeWarning, match="sanitizer \\[transfer\\]"):
        with transfer.guard("rec"):
            out = f(x)
    np.testing.assert_allclose(np.asarray(out), 2.0 * np.ones(3))
    snap = telemetry.metrics.snapshot()
    assert snap["sanitizer_transfer_violations"]["value"] >= 1
    kinds_msgs = runtime.violations("transfer")
    assert kinds_msgs and "item() forces a device->host sync" in kinds_msgs[0][1]


def test_transfers_free_outside_guard_and_when_off():
    sanitizer.configure("record")
    assert jnp.asarray(2.0).item() == 2.0  # outside any guard: legal
    sanitizer.configure("off")
    with transfer.guard("noop"):
        assert jnp.asarray(3.0).item() == 3.0  # guard is a no-op when off
    assert runtime.violations() == []


# ----------------------------------------------------- donation guard unit

def test_donation_poison_deletes_live_leaves():
    sanitizer.configure("record")
    telemetry.metrics.reset()
    state = {"w": jnp.ones(4), "b": jnp.zeros(2), "n": 3}
    assert donation.poison(state, label="unit state") == 2
    assert state["w"].is_deleted() and state["b"].is_deleted()
    st = donation.stats()
    assert (st["poisoned"], st["boundaries"]) == (2, 1)
    snap = telemetry.metrics.snapshot()
    assert snap["sanitizer_donation_poisoned"]["value"] == 2
    with pytest.raises(RuntimeError):
        np.asarray(state["w"])  # the read-after-donate now fails everywhere


def test_donation_poison_is_noop_when_off():
    sanitizer.configure("off")
    state = {"w": jnp.ones(4)}
    assert donation.poison(state) == 0
    assert not state["w"].is_deleted()


# --------------------------------------------------------- lockwatch unit

def test_lock_order_inversion_detected():
    sanitizer.configure("record")
    a = lockwatch.maybe_wrap(threading.Lock(), "A")
    b = lockwatch.maybe_wrap(threading.Lock(), "B")
    with a:
        with b:
            pass
    with pytest.warns(RuntimeWarning, match="inversion"):
        with b:
            with a:
                pass
    assert any("inversion" in m for _, m in runtime.violations("lock"))


def test_off_lock_notify_and_guarded_map():
    sanitizer.configure("record")
    cv = lockwatch.maybe_wrap(threading.Condition(), "cv")
    jobs = lockwatch.guard_map({}, cv, "jobs")
    with pytest.warns(RuntimeWarning, match="without holding"):
        with pytest.raises(RuntimeError):  # stock Condition still errors too
            cv.notify_all()
    jobs_before = len(runtime.violations("lock"))
    jobs["k"] = 1  # off-lock write: recorded, mutation still applied
    assert len(runtime.violations("lock")) == jobs_before + 1
    with cv:
        jobs["k2"] = 2  # under the lock: silent
    assert len(runtime.violations("lock")) == jobs_before + 1
    assert jobs == {"k": 1, "k2": 2}


def test_lockwatch_strict_raises():
    sanitizer.configure("strict")
    cv = lockwatch.maybe_wrap(threading.Condition(), "cv2")
    with pytest.raises(LockOrderViolation, match="without holding"):
        cv.notify_all()


def test_exclusive_flags_same_direction_concurrency():
    sanitizer.configure("record")
    sock = object()
    entered = threading.Event()
    release = threading.Event()

    def holder():
        with lockwatch.exclusive(sock, "send"):
            entered.set()
            release.wait(timeout=5)

    t = threading.Thread(target=holder)
    t.start()
    try:
        assert entered.wait(timeout=5)
        with pytest.warns(RuntimeWarning, match="concurrent send"):
            with lockwatch.exclusive(sock, "send"):
                pass
        # full duplex is legal: recv while the other thread sends
        before = len(runtime.violations("lock"))
        with lockwatch.exclusive(sock, "recv"):
            pass
        assert len(runtime.violations("lock")) == before
    finally:
        release.set()
        t.join(timeout=5)


def test_disabled_path_returns_stock_objects():
    sanitizer.configure("off")
    cv = threading.Condition()
    assert lockwatch.maybe_wrap(cv, "x") is cv
    m = lockwatch.guard_map({"a": 1}, cv, "x")
    assert type(m) is dict and m == {"a": 1}
    srv = PunchcardServer(port=0)
    assert isinstance(srv._cv, threading.Condition)
    assert type(srv.jobs) is dict


def test_punchcard_jobs_mutation_off_lock_is_flagged():
    sanitizer.configure("record")
    srv = PunchcardServer(port=0)
    assert isinstance(srv._cv, lockwatch.GuardedLock)
    with pytest.warns(RuntimeWarning, match="off-lock write"):
        srv.jobs["job-1"] = {"status": "QUEUED"}
    with srv._cv:
        srv.jobs["job-2"] = {"status": "QUEUED"}  # the blessed path
    assert len(runtime.violations("lock")) == 1


# ------------------------------------------- engine integration + the pins

def _lowered_epoch_text(eng, x, onehot, batch=16, window=2):
    state, xs, ys = _epoch_data(eng, x, onehot, batch, window)
    fn = eng._make_epoch_fn(xs.shape[1], window, True, xs.ndim)
    with eng.mesh:
        return fn.lower(state, xs, ys).as_text()


def test_disabled_and_enabled_lowering_byte_identical():
    """The zero-cost pin: the sanitizer is host-side instrumentation around
    dispatch, so the lowered program must be byte-identical with the flag
    off, on, and strict — it adds ZERO traced ops."""
    x, onehot = _toy()
    sanitizer.configure("off")
    off_a = _lowered_epoch_text(_engine(), x, onehot)
    off_b = _lowered_epoch_text(_engine(), x, onehot)
    assert off_a == off_b
    sanitizer.configure("record")
    assert _lowered_epoch_text(_engine(), x, onehot) == off_a
    sanitizer.configure("strict")
    assert _lowered_epoch_text(_engine(), x, onehot) == off_a


def test_engine_caches_flag_at_build():
    sanitizer.configure("off")
    eng = _engine()
    assert eng._sanitize is False
    sanitizer.configure("record")
    assert eng._sanitize is False  # snapshot taken at build, like _dynamics
    assert _engine()._sanitize is True


def test_clean_epoch_passes_strict_and_poisons_donated_state():
    sanitizer.configure("strict")
    x, onehot = _toy()
    eng = _engine()
    state0, xs, ys = _epoch_data(eng, x, onehot)
    state1, stats = eng.run_epoch(state0, xs, ys)
    assert np.all(np.isfinite(np.asarray(stats["loss"])))
    # the donated input state is poisoned at the step boundary: a stale read
    # now fails on CPU exactly as it would on a donating TPU backend
    leaves = [l for l in jax.tree.leaves(state0) if isinstance(l, jax.Array)]
    assert leaves and all(l.is_deleted() for l in leaves)
    assert donation.stats()["boundaries"] >= 1
    assert runtime.violations() == []


# ------------------------------------------------------- trainer seeded runs

def test_strict_trainer_raises_on_seeded_item_and_names_span():
    """The acceptance smoke: DISTKERAS_SANITIZE=strict turns a seeded
    in-loop ``.item()`` (DK101's target) into a raise that names the
    enclosing telemetry span."""
    telemetry.configure(True)  # spans on, so the violation is attributed
    sanitizer.configure("strict")
    x, onehot = _toy()
    t = dk.DOWNPOUR(_mlp(), loss=_leaky_loss(),
                    worker_optimizer=("sgd", {"learning_rate": 0.1}),
                    num_workers=2, batch_size=16, num_epoch=1,
                    communication_window=2, seed=7)
    with pytest.raises(TransferViolation, match="span 'step'") as exc:
        t.train(from_numpy(x, onehot))
    assert "hot loop" in str(exc.value)


def test_record_trainer_counts_seeded_item_and_warns():
    sanitizer.configure("record")
    telemetry.metrics.reset()
    x, onehot = _toy()
    t = dk.DOWNPOUR(_mlp(), loss=_leaky_loss(),
                    worker_optimizer=("sgd", {"learning_rate": 0.1}),
                    num_workers=2, batch_size=16, num_epoch=1,
                    communication_window=2, seed=7)
    with pytest.warns(RuntimeWarning, match="sanitizer"):
        t.train(from_numpy(x, onehot))  # completes despite the violation
    snap = telemetry.metrics.snapshot()
    assert snap["sanitizer_transfer_violations"]["value"] >= 1
    assert runtime.violations("transfer")
