"""fsdp x sp: seq-axis ZeRO center sharding composed with ring-attention
sequence parallelism in ONE WindowedEngine mesh (VERDICT r4 item 6 — the
long-context story meeting the memory story).

The reference's only strategy is parameter-server data parallelism
(distkeras/trainers.py per SURVEY.md §2); both fsdp and sequence
parallelism are beyond-reference capability, so the contract here is
internal consistency: fsdp=True on a (workers, seq) mesh must be a pure
LAYOUT change — the center variable stores 1/seq_shards per seq-row device
(HBM, not math), the training trajectory equals the replicated-center run,
and the whole thing still equals plain data parallelism within float
tolerance (sequence parallelism's existing contract,
tests/test_sequence_parallel.py).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import distkeras_tpu as dk
from distkeras_tpu.frame import from_numpy
from distkeras_tpu.models import FlaxModel, TransformerClassifier
from distkeras_tpu.parallel.mesh import SEQ_AXIS

from conftest import toy_text  # noqa: E402


def _model(seq_axis=None):
    return FlaxModel(TransformerClassifier(
        vocab_size=50, num_classes=2, dim=32, heads=2, num_layers=1,
        max_len=64, seq_axis=seq_axis,
    ))


def _train(seq_shards, seq_axis, fsdp, rule="downpour"):
    x, _, onehot = toy_text(n=128, seq=32)
    df = from_numpy(x, onehot)
    cls = {"downpour": dk.DOWNPOUR, "aeasgd": dk.AEASGD}[rule]
    kw = {"rho": 1.0, "learning_rate": 0.05} if rule == "aeasgd" else {}
    t = cls(_model(seq_axis), loss="categorical_crossentropy",
            worker_optimizer=("sgd", {"learning_rate": 0.05}),
            num_workers=4, batch_size=8, num_epoch=2,
            communication_window=2, seq_shards=seq_shards, fsdp=fsdp,
            seed=5, **kw)
    trained = t.train(df)
    return jax.tree.map(np.asarray, trained.params)


def test_fsdp_sp_trajectory_equals_replicated_sp():
    """fsdp is a layout change: same mesh, same math, same trajectory as the
    replicated-center sequence-parallel run (and the commit rule family
    doesn't matter — checked on a second, elastic-style rule)."""
    p_sp = _train(2, "seq", fsdp=False)
    p_fsdp = _train(2, "seq", fsdp=True)
    for a, b in zip(jax.tree.leaves(p_sp), jax.tree.leaves(p_fsdp)):
        np.testing.assert_allclose(a, b, rtol=2e-5, atol=1e-6)


def test_fsdp_sp_trajectory_equals_replicated_sp_aeasgd():
    p_sp = _train(2, "seq", fsdp=False, rule="aeasgd")
    p_fsdp = _train(2, "seq", fsdp=True, rule="aeasgd")
    for a, b in zip(jax.tree.leaves(p_sp), jax.tree.leaves(p_fsdp)):
        np.testing.assert_allclose(a, b, rtol=2e-5, atol=1e-6)


def test_fsdp_sp_matches_dp_within_tolerance():
    """The composed mesh still trains the SAME algorithm as plain dp."""
    p_dp = _train(1, None, fsdp=False)
    p_fsdp = _train(2, "seq", fsdp=True)
    for a, b in zip(jax.tree.leaves(p_dp), jax.tree.leaves(p_fsdp)):
        np.testing.assert_allclose(a, b, rtol=2e-3, atol=2e-4)


def test_fsdp_sp_center_is_sharded_over_seq():
    """The memory claim, verified on device layout: every evenly-splitting
    center leaf stores 1/seq_shards per device along its recorded dim, and
    gather_center re-assembles bit-identical full leaves."""
    from distkeras_tpu.algorithms import Downpour
    from distkeras_tpu.parallel.engine import WindowedEngine

    x, _, _ = toy_text(n=32, seq=32)
    eng = WindowedEngine(_model("seq"), "categorical_crossentropy", "sgd",
                         Downpour(2), num_workers=2, seq_shards=2, fsdp=True)
    state = eng.init_state(jax.random.PRNGKey(0), x[:4])

    dims = jax.tree.leaves(eng._center_fsdp_dims)
    leaves = jax.tree.leaves(state.center_params)
    assert any(d >= 0 for d in dims)  # the layout actually sharded something
    for d, leaf in zip(dims, leaves):
        spec = leaf.sharding.spec
        if d >= 0:
            assert SEQ_AXIS in tuple(spec), (d, spec, leaf.shape)
            shard = leaf.addressable_shards[0].data.shape
            assert shard[d] == leaf.shape[d] // 2, (d, shard, leaf.shape)
        else:
            assert SEQ_AXIS not in tuple(spec), (d, spec)

    full = eng.gather_center(state)
    for leaf, g in zip(leaves, jax.tree.leaves(full)):
        assert np.asarray(g).shape == leaf.shape


def test_fsdp_sp_state_from_center_resumes():
    """Elastic-resume path: a host-side center tree rebuilds a sharded state
    that trains (the restore goes straight into the sharded layout — no
    replicated spike)."""
    from conftest import epoch_data

    from distkeras_tpu.algorithms import Downpour
    from distkeras_tpu.parallel.engine import WindowedEngine

    x, _, onehot = toy_text(n=64, seq=32)
    eng = WindowedEngine(_model("seq"), "categorical_crossentropy", "sgd",
                         Downpour(2), num_workers=2, seq_shards=2, fsdp=True)
    state = eng.init_state(jax.random.PRNGKey(0), x[:4])
    center_host = jax.tree.map(np.asarray, eng.gather_center(state))

    eng2 = WindowedEngine(_model("seq"), "categorical_crossentropy", "sgd",
                          Downpour(2), num_workers=4, seq_shards=2, fsdp=True)
    st2 = eng2.state_from_center(
        jax.random.PRNGKey(1), center_host, eng2.rule.init_center_state(),
        {}, epoch=3)
    xs, ys = epoch_data(x, onehot, num_workers=4, n_windows=2, window=2, batch=4)
    xs, ys = eng2.shard_batches(xs, ys)
    st2, stats = eng2.run_epoch(st2, xs, ys)
    assert np.isfinite(np.asarray(stats["loss"])).all()
    assert int(st2.epoch) == 4


def test_fsdp_without_seq_shards_is_rejected_by_engine():
    from distkeras_tpu.algorithms import Downpour
    from distkeras_tpu.parallel.engine import WindowedEngine

    with pytest.raises(ValueError, match="GSPMD"):
        WindowedEngine(_model(None), "categorical_crossentropy", "sgd",
                       Downpour(2), num_workers=2, fsdp=True)


def test_tp_with_seq_shards_still_rejected():
    x, _, onehot = toy_text(n=32, seq=32)
    t = dk.DOWNPOUR(_model("seq"), loss="categorical_crossentropy",
                    num_workers=2, batch_size=8, num_epoch=1,
                    communication_window=2, seq_shards=2, tp_shards=2)
    with pytest.raises(ValueError, match="drop tp_shards"):
        t.train(from_numpy(x, onehot))
