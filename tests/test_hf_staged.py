"""gpt2_to_staged: HF GPT-2 checkpoints on the pipeline mesh.

Equality is the load-bearing claim: the converted StagedLM must produce the
HF model's OWN logits (same math, re-laid-out weights), not merely train.
Uses a small randomly-initialised FlaxGPT2LMHeadModel (no downloads — this
sandbox is offline; a pretrained checkpoint converts identically because
conversion is pure weight re-layout)."""

import jax
import numpy as np
import pytest

transformers = pytest.importorskip("transformers")

from distkeras_tpu.models import gpt2_to_staged
from distkeras_tpu.models.generate import (
    greedy_generate_staged,
    greedy_generate_staged_pipelined,
)


@pytest.fixture(scope="module")
def hf_model():
    cfg = transformers.GPT2Config(
        vocab_size=64, n_positions=32, n_embd=32, n_layer=4, n_head=2,
        embd_pdrop=0.0, attn_pdrop=0.0, resid_pdrop=0.0,
    )
    return transformers.FlaxGPT2LMHeadModel(cfg, seed=0)


def test_converted_logits_match_hf(hf_model):
    staged = gpt2_to_staged(hf_model, num_stages=2)
    params, _ = staged.init(jax.random.PRNGKey(0), np.zeros((1, 8), np.int32))

    rng = np.random.default_rng(0)
    tokens = rng.integers(0, 64, size=(3, 16)).astype(np.int32)
    ours, _ = staged.apply(params, {}, tokens)
    theirs = hf_model(tokens).logits
    np.testing.assert_allclose(
        np.asarray(ours), np.asarray(theirs), rtol=2e-4, atol=2e-5,
    )


def test_converted_decode_matches_hf_greedy(hf_model):
    """KV-cached greedy decode (sequential AND pipelined executors) must
    emit the tokens HF's own full-context argmax chooses."""
    staged = gpt2_to_staged(hf_model, num_stages=2)
    params, _ = staged.init(jax.random.PRNGKey(0), np.zeros((1, 8), np.int32))

    rng = np.random.default_rng(1)
    prompt = rng.integers(0, 64, size=(2, 5)).astype(np.int32)
    steps = 6

    ref = np.asarray(prompt)
    for _ in range(steps):
        nxt = np.argmax(np.asarray(hf_model(ref).logits)[:, -1], -1)
        ref = np.concatenate([ref, nxt[:, None].astype(np.int32)], axis=1)

    seq = greedy_generate_staged(staged, params, prompt, steps)
    np.testing.assert_array_equal(seq, ref)

    pp = greedy_generate_staged_pipelined(
        staged, params, prompt, steps, devices=jax.devices()[:2]
    )
    np.testing.assert_array_equal(pp, ref)


def test_converted_model_trains_on_pipeline_fsdp(hf_model):
    """The checkpoint becomes the initial center of a pipeline x fsdp
    trainer — the vocab-sharded embed/head path the conversion targets —
    and one epoch of DOWNPOUR moves it without breaking shard layout."""
    import distkeras_tpu as dk

    staged = gpt2_to_staged(hf_model, num_stages=2)
    rng = np.random.default_rng(2)
    x = rng.integers(0, 64, size=(64, 8)).astype(np.int32)
    df = dk.from_numpy(x, x)
    t = dk.DOWNPOUR(staged, loss="token_crossentropy",
                    worker_optimizer=("adam", {"learning_rate": 1e-3}),
                    num_workers=4, batch_size=8, num_epoch=2,
                    communication_window=2, pipeline_stages=2, fsdp=True)
    trained = t.train(df)
    h = t.get_history()["loss"]
    assert np.isfinite(h).all() and h[-1] < h[0], h
    # the trained center starts FROM the checkpoint: its embedding moved
    # from wte but stayed finite and vocab-shaped
    emb = np.asarray(trained.params["embed"]["tok_embed"]["embedding"])
    assert emb.shape == (64, 32) and np.isfinite(emb).all()


def test_untied_checkpoint_uses_its_own_head():
    """tie_word_embeddings=False checkpoints carry a separate lm_head; the
    conversion must use it, not wte^T (review finding: silently wrong
    logits otherwise)."""
    cfg = transformers.GPT2Config(
        vocab_size=48, n_positions=16, n_embd=16, n_layer=2, n_head=2,
        tie_word_embeddings=False,
    )
    model = transformers.FlaxGPT2LMHeadModel(cfg, seed=3)
    staged = gpt2_to_staged(model, num_stages=2)
    params, _ = staged.init(jax.random.PRNGKey(0), np.zeros((1, 4), np.int32))
    tokens = np.arange(8, dtype=np.int32).reshape(2, 4)
    ours, _ = staged.apply(params, {}, tokens)
    np.testing.assert_allclose(
        np.asarray(ours), np.asarray(model(tokens).logits),
        rtol=2e-4, atol=2e-5,
    )
    # and it genuinely differs from the tied mapping
    assert not np.allclose(
        params["head"]["out"]["kernel"],
        params["embed"]["tok_embed"]["embedding"].T,
    )


def test_conversion_rejects_mismatched_architectures(hf_model):
    with pytest.raises(ValueError, match="stages"):
        gpt2_to_staged(hf_model, num_stages=3)
    cfg = transformers.GPT2Config(
        vocab_size=32, n_embd=16, n_layer=2, n_head=2,
        activation_function="relu",
    )
    relu_model = transformers.FlaxGPT2LMHeadModel(cfg, seed=0)
    with pytest.raises(ValueError, match="GELU"):
        gpt2_to_staged(relu_model, num_stages=2)


def test_pretrained_pp_sp_twin_keeps_checkpoint(hf_model):
    """gpt2_to_staged(seq_axis=...) fine-tunes under pp x sp, and the
    TrainedModel _finalize hands back is a fully working adapter: seq_axis
    dropped (predict runs on a bare device) AND the attached checkpoint
    carried over — dataclasses.replace alone would lose the non-field
    ``_pretrained`` slot and a later ``init`` (e.g. continued training
    through a second trainer) would raise."""
    import distkeras_tpu as dk

    staged = gpt2_to_staged(hf_model, num_stages=2, seq_axis="seq")
    rng = np.random.default_rng(4)
    x = rng.integers(0, 64, size=(64, 8)).astype(np.int32)
    df = dk.from_numpy(x, x)
    t = dk.DOWNPOUR(staged, loss="token_crossentropy",
                    worker_optimizer=("adam", {"learning_rate": 1e-3}),
                    num_workers=2, batch_size=8, num_epoch=2,
                    communication_window=2, pipeline_stages=2, seq_shards=2)
    trained = t.train(df)
    h = t.get_history()["loss"]
    assert np.isfinite(h).all() and h[-1] < h[0], h
    assert trained.adapter.seq_axis is None
    # the twin still carries the checkpoint: init adopts it (no RuntimeError)
    params, _ = trained.adapter.init(None, x[:1])
    emb = np.asarray(params["embed"]["tok_embed"]["embedding"])
    assert emb.shape == (64, 32) and np.isfinite(emb).all()
    # ... and predict serves on a bare device
    out = trained.predict(x[:8])
    assert np.isfinite(np.asarray(out)).all()
