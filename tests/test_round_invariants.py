"""Round-exit invariants (VERDICT r4 item 5): no committed test or README
sentence may reference an evidence artifact that is not committed.

Round 4 shipped three failures of exactly this shape — an enforcement test
whose artifact was never produced, a protocol-versioned pin never re-pinned,
and a README claiming an artifact that didn't exist.  This test makes that
class of failure visible at AUTHORING time: it scans every test source and
README.md for round-artifact filenames (``<NAME>_r<N>.json``) and asserts
each referenced file exists at the repo root.
"""

import glob
import json
import os
import re

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
# mixed-case names too: `BENCH_full_r05.json` slipped through the original
# all-caps pattern while PERF.md claimed it (exactly the r4 failure class
# this file exists to catch)
ARTIFACT_RE = re.compile(r"\b([A-Z][A-Za-z0-9_]*_r\d+\.json)\b")


def _missing_in(path):
    with open(path) as fh:
        names = set(ARTIFACT_RE.findall(fh.read()))
    return sorted(n for n in names
                  if not os.path.exists(os.path.join(REPO, n)))


def test_every_test_referenced_artifact_exists():
    missing = {}
    for path in sorted(glob.glob(os.path.join(REPO, "tests", "*.py"))):
        gone = _missing_in(path)
        if gone:
            missing[os.path.basename(path)] = gone
    assert not missing, (
        f"tests reference uncommitted artifacts: {missing} — land the "
        "artifact in the same commit as the test that demands it"
    )


def test_readme_and_perf_artifact_claims_are_true():
    missing = {}
    for doc in ("README.md", "PERF.md"):
        gone = _missing_in(os.path.join(REPO, doc))
        if gone:
            missing[doc] = gone
    assert not missing, (
        f"docs claim artifacts that do not exist: {missing} — documentation "
        "written ahead of evidence is how saturated artifacts shipped in r3"
    )


def test_committed_round_artifacts_parse_and_carry_results():
    """Every committed round artifact parses; sweeps/accuracy artifacts are
    non-empty.  BENCH_full_* files are JSON-lines (one metric per line, the
    harness's one-line-per-metric contract); the rest are single documents."""
    for path in sorted(glob.glob(os.path.join(REPO, "*_r[0-9][0-9].json"))):
        name = os.path.basename(path)
        with open(path) as fh:
            if name.startswith("BENCH_full"):
                lines = [json.loads(l) for l in fh if l.strip()]
                assert lines, f"{name}: empty sweep"
                assert all("metric" in l for l in lines), name
            else:
                data = json.load(fh)
                if name.startswith("ACCURACY"):
                    assert data.get("results"), f"{name}: empty results"
