"""Signal-plane tests: rollup ring windowing (counter rates, histogram
quantiles from cumulative-bucket deltas, gauge breach fractions), the
quantile/breach estimators (exact on bucket boundaries, monotone across
carry-forward merges of different ladders), SLO burn-rate alerting on
synthetic breach/recovery traces (multi-window fire/resolve + the incident
JSONL), the ``slo_*``/``alert_*`` schema golden, the flag-off pin (no
``DISTKERAS_ROLLUP`` => no ring, no engine, untouched loops), and the
``dkmon`` CLI gate contract.  No jax import, no devices."""

import json
import os
import sys

import pytest

from distkeras_tpu import telemetry
from distkeras_tpu.online.scheduler import WindowScheduler
from distkeras_tpu.telemetry import slo
from distkeras_tpu.telemetry.flightdeck import correlate
from distkeras_tpu.telemetry.flightdeck import rollup
from distkeras_tpu.telemetry.flightdeck.recorder import recorder
from distkeras_tpu.telemetry.metrics import (
    Registry,
    _merge_histograms,
    merge_snapshots,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GOLDEN = os.path.join(REPO_ROOT, "tests", "golden")

sys.path.insert(0, REPO_ROOT)

from tools import dkmon  # noqa: E402
from tools.dkmon.__main__ import main as dkmon_main  # noqa: E402


@pytest.fixture(autouse=True)
def clean_signal_plane(tmp_path, monkeypatch):
    """Telemetry on, rollups off (tests opt in per-case), fixed run_id,
    and every module-global env-driven again on the way out."""
    monkeypatch.setenv("DISTKERAS_TELEMETRY_DIR", str(tmp_path))
    monkeypatch.delenv("DISTKERAS_SLO_INCIDENTS", raising=False)
    telemetry.configure(True)
    rollup.configure(False)
    telemetry.metrics.reset()
    recorder.reset()
    correlate.set_run_id("testrun")
    yield
    rollup.stop()
    rollup.configure(None)
    slo.reset_engines()
    telemetry.metrics.reset()
    recorder.reset()
    correlate.set_run_id(None)
    telemetry.configure(None)


def _hist(buckets, count=None, total=None):
    """Cumulative-bucket histogram payload in snapshot shape."""
    n = count if count is not None else max(buckets.values(), default=0)
    return {"type": "histogram", "sum": total or 0.0, "count": n,
            "buckets": dict(buckets)}


def _ring(interval=1.0, capacity=256):
    return rollup.RollupRing(registry=Registry(), interval=interval,
                             capacity=capacity, clock=lambda: 0.0)


# -------------------------------------------------- quantile estimation


def test_quantile_exact_on_bucket_boundaries():
    buckets = {"0.1": 4, "0.25": 8, "+Inf": 8}
    # rank q*total landing exactly on a cumulative count returns that
    # bucket's upper bound, not an interpolation
    assert rollup.quantile_from_cumulative(buckets, 0.5) == 0.1
    assert rollup.quantile_from_cumulative(buckets, 1.0) == 0.25
    # inside the (0.1, 0.25] bucket: linear from the previous bound
    assert rollup.quantile_from_cumulative(buckets, 0.75) == pytest.approx(
        0.175)
    # q=0 sits at the lower edge of the first populated bucket
    assert rollup.quantile_from_cumulative(buckets, 0.0) == 0.0


def test_quantile_overflow_clamps_to_top_finite_bound():
    buckets = {"0.1": 2, "+Inf": 4}
    # ranks in the +Inf overflow cannot be resolved past the ladder's top
    # rung; the clamp keeps the answer finite and threshold-comparable
    assert rollup.quantile_from_cumulative(buckets, 1.0) == 0.1


def test_quantile_skips_empty_buckets():
    buckets = {"0.05": 0, "0.1": 0, "0.25": 6, "+Inf": 6}
    assert rollup.quantile_from_cumulative(buckets, 0.0) == pytest.approx(0.1)
    assert rollup.quantile_from_cumulative(buckets, 1.0) == 0.25


def test_quantile_monotone_in_q_and_input_validation():
    buckets = {"0.01": 3, "0.1": 7, "0.5": 11, "2.5": 12, "+Inf": 13}
    grid = [rollup.quantile_from_cumulative(buckets, q / 20)
            for q in range(21)]
    assert grid == sorted(grid)
    assert rollup.quantile_from_cumulative({}, 0.5) == 0.0
    assert rollup.quantile_from_cumulative({"0.1": 0, "+Inf": 0}, 0.5) == 0.0
    with pytest.raises(ValueError):
        rollup.quantile_from_cumulative(buckets, 1.5)


def test_quantile_monotone_across_merge_of_different_ladders():
    """Carry-forward union of two ladders only ever moves cumulative counts
    up, so merged quantiles stay exact on shared boundaries and bracketed
    by the per-job answers elsewhere."""
    a = _hist({"0.1": 10, "+Inf": 10})          # all ten under 100ms
    b = _hist({"0.25": 10, "+Inf": 10})         # all ten under 250ms
    merged = _merge_histograms([a, b])
    assert merged["buckets"] == {"0.1": 10, "0.25": 20, "+Inf": 20}
    # p50 of the merge = a's contribution, boundary-exact
    assert rollup.quantile_from_cumulative(merged["buckets"], 0.5) == 0.1
    assert rollup.quantile_from_cumulative(merged["buckets"], 1.0) == 0.25
    lo = min(rollup.quantile_from_cumulative(a["buckets"], 0.9),
             rollup.quantile_from_cumulative(b["buckets"], 0.9))
    hi = max(rollup.quantile_from_cumulative(a["buckets"], 0.9),
             rollup.quantile_from_cumulative(b["buckets"], 0.9))
    got = rollup.quantile_from_cumulative(merged["buckets"], 0.9)
    assert lo <= got <= hi
    grid = [rollup.quantile_from_cumulative(merged["buckets"], q / 20)
            for q in range(21)]
    assert grid == sorted(grid)


def test_breach_fraction_boundary_exact_and_interpolated():
    buckets = {"0.1": 4, "0.25": 8, "+Inf": 8}
    # threshold on a boundary: exactly the observations beyond that bucket
    assert slo.breach_fraction_from_cumulative(buckets, 0.1) == 0.5
    assert slo.breach_fraction_from_cumulative(buckets, 0.25) == 0.0
    # inside a bucket: linear interpolation of the cumulative count
    assert slo.breach_fraction_from_cumulative(buckets, 0.175) == \
        pytest.approx(0.25)
    assert slo.breach_fraction_from_cumulative({}, 0.1) == 0.0


def test_breach_fraction_counts_overflow_conservatively():
    buckets = {"0.1": 2, "+Inf": 8}
    # 6 observations in +Inf breach any threshold above the top rung
    assert slo.breach_fraction_from_cumulative(buckets, 0.2) == 0.75


# ------------------------------------------------------- the rollup ring


def test_window_rate_spans_the_full_window():
    ring = _ring()
    c = ring.registry.counter("reqs_total", help="x")
    ring.tick(now=0.0)
    c.inc(50)
    ring.tick(now=10.0)
    c.inc(100)
    ring.tick(now=20.0)
    # the tick at-or-before the window start anchors the delta, so a 20s
    # window measures 20s of increase, not just the in-window ticks
    assert ring.window_rate("reqs_total", 20.0, now=20.0) == pytest.approx(7.5)
    assert ring.window_rate("reqs_total", 10.0, now=20.0) == pytest.approx(
        10.0)
    # counter reset (restart) clamps to zero instead of a negative rate
    ring.ingest(30.0, {"reqs_total": {"type": "counter", "value": 0}})
    assert ring.window_rate("reqs_total", 10.0, now=30.0) == 0.0
    # one usable tick is not a rate
    assert ring.window_rate("reqs_total", 5.0, now=100.0) is None


def test_window_quantile_from_bucket_deltas():
    ring = _ring()
    ring.ingest(0.0, {"lat": _hist({"0.1": 100, "0.25": 100, "+Inf": 100})})
    # between t=0 and t=10: 4 new obs <= 0.1, 4 more in (0.1, 0.25]
    ring.ingest(10.0, {"lat": _hist({"0.1": 104, "0.25": 108, "+Inf": 108})})
    delta = ring.window_delta("lat", 10.0, now=10.0)
    assert delta["count"] == 8
    assert delta["buckets"] == {"0.1": 4, "0.25": 8, "+Inf": 8}
    # history before the window never leaks in: the old 100 obs are gone
    assert ring.window_quantile("lat", 0.5, 10.0, now=10.0) == 0.1
    assert ring.window_quantile("lat", 1.0, 10.0, now=10.0) == 0.25
    # a quiet window (no new observations) is None, not 0-latency
    ring.ingest(20.0, {"lat": _hist({"0.1": 104, "0.25": 108, "+Inf": 108})})
    assert ring.window_quantile("lat", 10.0, 10.0, now=20.0) is None


def test_window_breach_fraction_both_ops():
    ring = _ring()
    for t, v in [(0.0, 0.0), (1.0, 0.0), (2.0, 5.0), (3.0, 5.0)]:
        ring.ingest(t, {"lag": {"type": "gauge", "value": v}})
    # the tick at exactly now-window anchors the window (inclusive start)
    assert ring.window_breach_fraction("lag", 2.0, 1.0, now=3.0) == 1.0
    assert ring.window_breach_fraction("lag", 2.0, 2.0, now=3.0) == \
        pytest.approx(2 / 3)
    assert ring.window_breach_fraction("lag", 2.0, 3.0, now=3.0) == 0.5
    # ticks after `now` never count (injected clocks, skewed job clocks)
    assert ring.window_breach_fraction("lag", 2.0, 1.0, now=1.0) == 0.0
    # op="lt": a healthy-replica count breaching *below* the floor
    assert ring.window_breach_fraction("lag", 2.0, 1.0, now=3.0,
                                       op="lt") == 0.0
    assert ring.window_breach_fraction("lag", 6.0, 1.0, now=3.0,
                                       op="lt") == 1.0
    assert ring.window_breach_fraction("nope", 1.0, 2.0, now=3.0) is None
    with pytest.raises(ValueError):
        ring.window_breach_fraction("lag", 1.0, 2.0, now=3.0, op="ge")


def test_ring_capacity_evicts_oldest():
    ring = rollup.RollupRing(registry=Registry(), interval=1.0, capacity=4,
                             clock=lambda: 0.0)
    for t in range(6):
        ring.ingest(float(t), {"g": {"type": "gauge", "value": float(t)}})
    assert len(ring) == 4
    assert [unix for unix, _ in ring.samples()] == [2.0, 3.0, 4.0, 5.0]
    assert [unix for unix, _ in ring.samples(since=4.0)] == [4.0, 5.0]


def test_export_filters_and_merge_series():
    ring = _ring()
    ring.ingest(10.0, {"a_total": {"type": "counter", "value": 1},
                       "g": {"type": "gauge", "value": 3.0}})
    out = ring.export(since=5.0, names=["a_total"])
    assert out["interval"] == 1.0
    assert [s["metrics"] for s in out["samples"]] == [
        {"a_total": {"type": "counter", "value": 1}}]
    # two jobs' rings merged onto one axis: same-bin counters sum, gauges
    # keep max + fleet mean — the same algebra as the /metrics fleet merge
    job_b = _ring()
    job_b.ingest(10.4, {"a_total": {"type": "counter", "value": 2},
                        "g": {"type": "gauge", "value": 5.0}})
    merged = rollup.merge_series([ring.export(), job_b.export()], align_s=1.0)
    assert len(merged["samples"]) == 1
    metrics = merged["samples"][0]["metrics"]
    assert metrics["a_total"] == {"type": "counter", "value": 3}
    assert metrics["g"]["value"] == 5.0 and metrics["g"]["mean"] == 4.0
    # distinct bins stay distinct — absence of a tick is itself a signal
    job_b.ingest(12.0, {"g": {"type": "gauge", "value": 1.0}})
    merged = rollup.merge_series([ring.export(), job_b.export()], align_s=1.0)
    assert [s["unix"] for s in merged["samples"]] == [10.0, 12.0]


def test_ring_tick_reuses_registry_snapshot_shapes():
    ring = _ring()
    ring.registry.counter("ticks_total", help="x").inc(3)
    ring.registry.histogram("lat_seconds", help="x").observe(0.07)
    ring.tick(now=1.0)
    (_, snap), = ring.samples()
    assert snap["ticks_total"] == {"type": "counter", "value": 3}
    assert snap["lat_seconds"]["count"] == 1
    # snapshots merge with the registry's own fleet algebra
    merged = merge_snapshots([snap, snap])
    assert merged["ticks_total"]["value"] == 6


# --------------------------------------------- burn-rate fire and resolve


def _breach_trace():
    """A ring with one gauge: healthy (0) for t<20, breaching (9) for
    t in [20, 27], recovered from t=28 — one tick per second."""
    ring = _ring()
    for t in range(41):
        v = 9.0 if 20 <= t <= 27 else 0.0
        ring.ingest(float(t), {"lag_seconds": {"type": "gauge", "value": v}})
    return ring


def _lag_objective(**kw):
    defaults = dict(name="lag", kind="gauge", metric="lag_seconds",
                    threshold=1.0, op="gt", target=0.9, fast_window_s=4.0,
                    slow_window_s=16.0, burn_threshold=2.0)
    defaults.update(kw)
    return slo.SLOConfig(**defaults)


def test_fast_window_breach_alone_does_not_fire(tmp_path):
    engine = slo.SLOEngine([_lag_objective()], source="t", ring=_breach_trace(),
                           registry=Registry(), clock=lambda: 22.0,
                           incident_file=str(tmp_path / "inc.jsonl"))
    status = engine.evaluate()
    row, = status["objectives"]
    # fast window (t 18..22): 3/5 bad -> burn 6; slow (t 6..22): 3/17 -> 1.76
    assert row["burn_fast"] == pytest.approx(6.0)
    assert row["burn_slow"] == pytest.approx((3 / 17) / 0.1)
    assert row["burn_slow"] < 2.0
    assert not row["firing"] and row["since"] is None
    assert not os.path.exists(tmp_path / "inc.jsonl")


def test_fire_then_resolve_writes_incident_pair(tmp_path):
    path = tmp_path / "inc.jsonl"
    now = {"t": 27.0}
    engine = slo.SLOEngine([_lag_objective()], source="t",
                           ring=_breach_trace(), registry=Registry(),
                           clock=lambda: now["t"], incident_file=str(path))
    row, = engine.evaluate()["objectives"]
    # both windows over threshold at t=27: fast 5/5 -> 10, slow 8/17 -> 4.7
    assert row["burn_fast"] == pytest.approx(10.0)
    assert row["burn_slow"] == pytest.approx((8 / 17) / 0.1)
    assert row["firing"] and row["since"] == 27.0
    # steady state: still firing, but no duplicate incident line
    engine.evaluate()
    # recovery at t=33: fast window clean resolves even while the slow
    # window still carries the breach
    now["t"] = 33.0
    row, = engine.evaluate()["objectives"]
    assert row["burn_fast"] == 0.0
    assert row["burn_slow"] >= 2.0
    assert not row["firing"] and row["since"] is None

    records = [json.loads(line) for line in open(path)]
    assert [r["event"] for r in records] == ["fire", "resolve"]
    fire = records[0]
    assert fire["objective"] == "lag" and fire["source"] == "t"
    assert fire["run_id"] == "testrun"
    assert fire["unix"] == 27.0
    assert fire["burn_fast"] == pytest.approx(10.0)
    assert fire["burn_threshold"] == 2.0
    assert isinstance(fire["trace_ids"], list)


def test_no_data_is_distinct_from_healthy(tmp_path):
    engine = slo.SLOEngine([_lag_objective(metric="never_seen")], source="t",
                           ring=_ring(), registry=Registry(),
                           clock=lambda: 10.0,
                           incident_file=str(tmp_path / "inc.jsonl"))
    row, = engine.evaluate()["objectives"]
    assert row["burn_fast"] is None and row["burn_slow"] is None
    assert not row["firing"]


def test_ratio_objective_burns_on_shed_rate(tmp_path):
    ring = _ring()
    routed = sheds = 0
    for t in range(31):
        routed += 10
        if t > 10:
            sheds += 5  # one third of traffic shed from t=11 on
        ring.ingest(float(t), {
            "routed_total": {"type": "counter", "value": routed},
            "sheds_total": {"type": "counter", "value": sheds},
        })
    obj = slo.SLOConfig(
        name="shed", kind="ratio", bad_metric="sheds_total",
        total_metric=("routed_total", "sheds_total"), target=0.99,
        fast_window_s=5.0, slow_window_s=20.0, burn_threshold=2.0)
    engine = slo.SLOEngine([obj], source="t", ring=ring, registry=Registry(),
                           clock=lambda: 30.0,
                           incident_file=str(tmp_path / "inc.jsonl"))
    row, = engine.evaluate()["objectives"]
    assert row["bad_fast"] == pytest.approx(1 / 3)
    assert row["burn_fast"] == pytest.approx((1 / 3) / 0.01)
    assert row["firing"]


def test_quantile_objective_reads_window_deltas(tmp_path):
    ring = _ring()
    ring.ingest(0.0, {"lat_seconds": _hist({"0.1": 50, "0.25": 50,
                                            "+Inf": 50})})
    # all 20 in-window observations land in (0.1, 0.25]: p99 ~ 0.25
    ring.ingest(8.0, {"lat_seconds": _hist({"0.1": 50, "0.25": 70,
                                            "+Inf": 70})})
    obj = slo.SLOConfig(name="p99", kind="quantile", metric="lat_seconds",
                        quantile=0.99, threshold=0.1, target=0.9,
                        fast_window_s=10.0, slow_window_s=40.0,
                        burn_threshold=2.0)
    engine = slo.SLOEngine([obj], source="t", ring=ring, registry=Registry(),
                           clock=lambda: 10.0,
                           incident_file=str(tmp_path / "inc.jsonl"))
    row, = engine.evaluate()["objectives"]
    assert row["bad_fast"] == 1.0  # every observation above the threshold
    assert row["burn_fast"] == pytest.approx(10.0)
    assert row["observed"] == pytest.approx(0.2485)


# ----------------------------------------------------- schema and wiring


def test_slo_metrics_schema_golden():
    registry = Registry()
    m = slo.slo_metrics(registry)
    m["objectives"].set(5)
    m["evaluations"].inc(12)
    m["burning"].set(1)
    m["burn_max"].set(10.5)
    m["firing"].set(1)
    m["fired"].inc(2)
    m["resolved"].inc(1)
    m["incidents"].inc(3)
    golden = open(os.path.join(GOLDEN, "slo_metrics.txt")).read()
    assert registry.to_prometheus(labels={"run_id": "fleet1234"}) == golden
    # get-or-create: a second call hands back the same instruments
    assert slo.slo_metrics(registry)["fired"] is m["fired"]


def test_engine_drives_canonical_instruments(tmp_path):
    registry = Registry()
    engine = slo.SLOEngine([_lag_objective()], source="t",
                           ring=_breach_trace(), registry=registry,
                           clock=lambda: 27.0,
                           incident_file=str(tmp_path / "inc.jsonl"))
    slo._ENGINES["t"] = engine  # fleet gauges read the registered set
    try:
        engine.evaluate()
        snap = registry.snapshot()
        assert snap["slo_evaluations_total"]["value"] == 1
        assert snap["slo_objectives"]["value"] == 1
        assert snap["slo_burning"]["value"] == 1
        assert snap["slo_burn_rate_max"]["value"] == pytest.approx(10.0)
        assert snap["alert_firing"]["value"] == 1
        assert snap["alert_fired_total"]["value"] == 1
        assert snap["alert_incidents_total"]["value"] == 1
    finally:
        slo.reset_engines()


def test_incident_path_honors_env_and_run_id(monkeypatch):
    assert slo.incident_path().endswith("incidents_testrun.jsonl")
    monkeypatch.setenv("DISTKERAS_SLO_INCIDENTS", "/tmp/custom.jsonl")
    assert slo.incident_path() == "/tmp/custom.jsonl"


def test_slo_config_validation():
    with pytest.raises(ValueError):
        slo.SLOConfig(name="x", kind="nope")
    with pytest.raises(ValueError):
        slo.SLOConfig(name="x", kind="gauge")  # needs a metric
    with pytest.raises(ValueError):
        slo.SLOConfig(name="x", kind="ratio", bad_metric="b")  # needs totals
    with pytest.raises(ValueError):
        slo.SLOConfig(name="x", kind="gauge", metric="m", target=1.0)
    with pytest.raises(ValueError):
        slo.SLOConfig(name="x", kind="gauge", metric="m",
                      fast_window_s=60.0, slow_window_s=30.0)
    with pytest.raises(ValueError):
        slo.SLOEngine([_lag_objective(), _lag_objective()])
    cfg = slo.SLOConfig(name="x", kind="gauge", metric="m", target=0.9)
    assert cfg.budget == pytest.approx(0.1)


def test_default_objectives_cover_shipped_metrics():
    serving = slo.default_serving_objectives()
    assert [o.name for o in serving] == [
        "serving_ttft_p99", "serving_tier_latency_p99",
        "serving_tier_replicas_available", "serving_tier_shed_ratio"]
    by_name = {o.name: o for o in serving}
    assert by_name["serving_tier_replicas_available"].op == "lt"
    online, = slo.default_online_objectives(30.0)
    assert online.metric == "online_window_lag_seconds"
    assert online.threshold == 60.0


# ------------------------------------------------------- the flag-off pin


def test_rollup_flag_off_is_inert():
    # fixture set rollup.configure(False): telemetry on, rollups off
    assert rollup.interval() is None
    assert rollup.ensure_rollup() is None
    assert rollup.rollup_ring() is None
    assert slo.maybe_engine([_lag_objective()], source="t") is None
    ctype, body, code = rollup.timeseries_view({"query": ""})
    assert code == 200
    assert json.loads(body) == {"enabled": False, "samples": []}


def test_telemetry_off_wins_over_rollup_env(monkeypatch):
    telemetry.configure(False)
    rollup.configure(1.0)
    assert rollup.ensure_rollup() is None
    assert slo.maybe_engine([_lag_objective()], source="t") is None


def test_scheduler_flag_off_path_never_builds_an_engine(tmp_path):
    sched = WindowScheduler(str(tmp_path / "cap"), lambda w, s: None,
                            str(tmp_path / "ckpt"), poll_interval=0.05)
    sched.start()
    try:
        assert sched._slo is None
    finally:
        sched.stop()


def test_rollup_env_parsing(monkeypatch):
    rollup.configure(None)
    monkeypatch.setenv("DISTKERAS_ROLLUP", "2.5")
    assert rollup.interval() == 2.5
    rollup.configure(None)
    monkeypatch.setenv("DISTKERAS_ROLLUP", "off")
    assert rollup.interval() is None
    rollup.configure(False)  # leave it off for the fixture teardown


def test_ensure_rollup_starts_one_shared_ring():
    rollup.configure(0.05)
    ring = rollup.ensure_rollup()
    try:
        assert ring is not None
        assert rollup.ensure_rollup() is ring  # idempotent
        assert rollup.rollup_ring() is ring
        engine = slo.maybe_engine([_lag_objective()], source="t")
        assert engine is not None and engine.ring is ring
        assert slo.engines()["t"] is engine
    finally:
        rollup.stop()
        slo.reset_engines()
        rollup.configure(False)
    assert rollup.rollup_ring() is None


def test_slo_view_serves_registered_engines(tmp_path):
    engine = slo.SLOEngine([_lag_objective()], source="t",
                           ring=_breach_trace(), registry=Registry(),
                           clock=lambda: 27.0,
                           incident_file=str(tmp_path / "inc.jsonl"))
    slo._ENGINES["t"] = engine
    try:
        engine.evaluate()
        ctype, body, code = slo.slo_view()
        assert (ctype, code) == ("application/json", 200)
        payload = json.loads(body)
        assert payload["enabled"] and payload["run_id"] == "testrun"
        row, = payload["engines"]["t"]["objectives"]
        assert row["name"] == "lag" and row["firing"]
    finally:
        slo.reset_engines()


# ------------------------------------------------------------------ dkmon


def _incident_lines(path, *events):
    with open(path, "w") as fh:
        for i, (event, objective) in enumerate(events):
            fh.write(json.dumps({
                "event": event, "objective": objective, "source": "t",
                "unix": 100.0 + i, "run_id": "testrun",
                "burn_fast": 10.0, "burn_slow": 4.0, "burn_threshold": 2.0,
                "threshold": 1.0, "observed": None, "trace_ids": [],
            }) + "\n")
    return str(path)


def test_load_incidents_skips_torn_lines(tmp_path):
    path = _incident_lines(tmp_path / "inc.jsonl", ("fire", "lag"))
    with open(path, "a") as fh:
        fh.write('{"event": "reso')  # a torn trailing write
    records = dkmon.load_incidents(path)
    assert len(records) == 1 and records[0]["event"] == "fire"


def test_firing_from_incidents_pairs_fire_with_resolve(tmp_path):
    records = dkmon.load_incidents(_incident_lines(
        tmp_path / "inc.jsonl",
        ("fire", "lag"), ("resolve", "lag"), ("fire", "shed")))
    firing = dkmon.firing_from_incidents(records)
    assert [r["objective"] for r in firing] == ["shed"]


def test_render_status_table(tmp_path):
    engine = slo.SLOEngine([_lag_objective()], source="t",
                           ring=_breach_trace(), registry=Registry(),
                           clock=lambda: 27.0,
                           incident_file=str(tmp_path / "inc.jsonl"))
    status = engine.evaluate()
    out = dkmon.render_status({"tier:t": status})
    assert "FIRING since 27" in out
    assert "1 objective(s), 1 firing" in out
    assert dkmon.firing_rows({"tier:t": status})[0]["engine"] == "tier:t"
    # rollups-off engines render a placeholder row, not a crash
    off = dkmon.render_status({"x": {"enabled": False}})
    assert "(rollups off)" in off


def test_dkmon_check_gates_on_incident_log(tmp_path, capsys):
    path = _incident_lines(tmp_path / "inc.jsonl", ("fire", "lag"))
    assert dkmon_main(["check", "--incidents", path]) == 2
    assert "FIRING lag" in capsys.readouterr().err
    _incident_lines(tmp_path / "inc.jsonl",
                    ("fire", "lag"), ("resolve", "lag"))
    assert dkmon_main(["check", "--incidents", path]) == 0
    assert "no firing alerts" in capsys.readouterr().out


def test_dkmon_source_error_exits_3(tmp_path, capsys):
    missing = str(tmp_path / "nope.jsonl")
    assert dkmon_main(["check", "--incidents", missing]) == 3
    assert "error" in capsys.readouterr().err
    assert dkmon_main(["status", "--incidents", missing]) == 3


def test_dkmon_status_renders_incident_log(tmp_path, capsys):
    path = _incident_lines(tmp_path / "inc.jsonl", ("fire", "lag"))
    assert dkmon_main(["status", "--incidents", path]) == 0
    out = capsys.readouterr().out
    assert "1 incident record(s)" in out and "FIRING lag" in out
    assert dkmon_main(["status", "--incidents", path, "--json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["firing"][0]["objective"] == "lag"


def test_daemon_slo_status_verb_carries_local_engines(tmp_path):
    from distkeras_tpu.job_deployment import Job, PunchcardServer

    engine = slo.SLOEngine([_lag_objective()], source="tier",
                           ring=_breach_trace(), registry=Registry(),
                           clock=lambda: 27.0,
                           incident_file=str(tmp_path / "inc.jsonl"))
    slo._ENGINES["tier"] = engine
    engine.evaluate()
    server = PunchcardServer(port=0, secret="s3cret")
    server.start()
    try:
        reply = Job("127.0.0.1", server.port, secret="s3cret").slo_status()
        assert reply["status"] == "ok"
        assert reply["firing_count"] == 1
        row, = reply["engines"]["daemon:tier"]["objectives"]
        assert row["name"] == "lag" and row["firing"]
        assert reply["firing"][0]["owner"] == "daemon"
        assert reply["timeseries"]["samples"] == []
        # the fleet view feeds dkmon's daemon source unchanged
        view = dkmon.fetch_daemon("127.0.0.1", server.port, secret="s3cret")
        assert [r["name"] for r in dkmon.firing_rows(view["engines"])] == \
            ["lag"]
    finally:
        server.stop()
        slo.reset_engines()
