"""FSDP / ZeRO-3 center sharding (GSPMD engine, ``fsdp=True``): the
parameter-server center variable is stored sharded over the *workers* mesh
axis instead of replicated, gathered at use by the XLA partitioner.

The reference replicates its center on the driver by construction
(``distkeras/parameter_servers.py`` holds one full weight copy); FSDP is a
beyond-reference capability of the rebuild.  These tests pin the contract:
sharding the center changes *where bytes live*, never *what is computed* —
the FSDP training trajectory must match the plain data-parallel one."""

import jax
import numpy as np
import pytest

import distkeras_tpu as dk
from distkeras_tpu.algorithms import Downpour, DynSGD
from distkeras_tpu.frame import from_numpy
from distkeras_tpu.models import MLP, FlaxModel
from distkeras_tpu.parallel import TP_AXIS, GSPMDEngine, WindowedEngine
from distkeras_tpu.parallel.mesh import WORKER_AXIS


def _data(n=256, d=16, classes=4, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = np.argmax(x @ rng.normal(size=(d, classes)), axis=1).astype(np.int32)
    return x, y, np.eye(classes, dtype=np.float32)[y]


def _epoch_arrays(x, onehot, num_workers, n_windows, window, batch):
    n = num_workers * n_windows * window * batch
    xs = x[:n].reshape(num_workers, n_windows, window, batch, -1)
    ys = np.argmax(onehot[:n], -1).reshape(num_workers, n_windows, window, batch)
    return xs, ys.astype(np.int32)


def _run(engine, xs_np, ys_np, x0, epochs=2):
    state = engine.init_state(jax.random.PRNGKey(0), x0)
    xs, ys = engine.shard_batches(xs_np, ys_np)
    for _ in range(epochs):
        state, stats = engine.run_epoch(state, xs, ys)
    return (jax.tree.map(np.asarray, state.center_params),
            np.asarray(stats["loss"]))


def _assert_trees_close(a, b):
    flat_a, flat_b = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(flat_a) == len(flat_b)
    for x, y in zip(flat_a, flat_b):
        np.testing.assert_allclose(x, y, rtol=2e-5, atol=2e-6)


def test_fsdp_matches_dp_trajectory():
    """4 workers with a workers-axis-sharded center computes the same
    training run as 4 workers with a replicated center."""
    x, y, onehot = _data()
    adapter = lambda: FlaxModel(MLP(features=(32, 16), num_classes=4))
    xs, ys = _epoch_arrays(x, onehot, num_workers=4, n_windows=2, window=4, batch=8)

    dp = WindowedEngine(adapter(), "categorical_crossentropy",
                        ("sgd", {"learning_rate": 0.05}), Downpour(4),
                        num_workers=4, metrics=())
    fs = GSPMDEngine(adapter(), "categorical_crossentropy",
                     ("sgd", {"learning_rate": 0.05}), Downpour(4),
                     num_workers=4, fsdp=True, metrics=())
    p_dp, loss_dp = _run(dp, xs, ys, x[:8])
    p_fs, loss_fs = _run(fs, xs, ys, x[:8])
    _assert_trees_close(p_dp, p_fs)
    np.testing.assert_allclose(loss_dp, loss_fs, rtol=2e-5, atol=2e-6)


def test_fsdp_center_actually_sharded():
    """Every center kernel with a dim that splits over 4 workers stores
    sharded; each device holds 1/4 of those leaves."""
    x, _, onehot = _data()
    engine = GSPMDEngine(FlaxModel(MLP(features=(32, 16), num_classes=4)),
                         "categorical_crossentropy", "sgd", Downpour(4),
                         num_workers=4, fsdp=True, metrics=())
    state = engine.init_state(jax.random.PRNGKey(0), x[:8])
    specs = [
        (leaf.shape, leaf.sharding.spec)
        for leaf in jax.tree.leaves(state.center_params)
    ]
    on_workers = [
        shape for shape, s in specs
        if WORKER_AXIS in jax.tree.leaves(tuple(s))
    ]
    shardable = [
        shape for shape, _ in specs
        if any(d % 4 == 0 and d >= 8 for d in shape)
    ]
    assert len(on_workers) == len(shardable) and shardable, specs


def test_fsdp_composes_with_tp():
    """(2 workers x 2 model) with the center sharded over BOTH axes still
    computes the data-parallel trajectory."""
    x, y, onehot = _data()
    adapter = lambda: FlaxModel(MLP(features=(32, 16), num_classes=4))
    xs, ys = _epoch_arrays(x, onehot, num_workers=2, n_windows=2, window=4, batch=8)

    dp = WindowedEngine(adapter(), "categorical_crossentropy",
                        ("sgd", {"learning_rate": 0.05}), Downpour(4),
                        num_workers=2, metrics=())
    both = GSPMDEngine(adapter(), "categorical_crossentropy",
                       ("sgd", {"learning_rate": 0.05}), Downpour(4),
                       num_workers=2, tp_shards=2, fsdp=True, metrics=())
    p_dp, loss_dp = _run(dp, xs, ys, x[:8])
    p_b, loss_b = _run(both, xs, ys, x[:8])
    _assert_trees_close(p_dp, p_b)
    np.testing.assert_allclose(loss_dp, loss_b, rtol=2e-5, atol=2e-6)
    # at least one leaf carries both mesh axes
    state = both.init_state(jax.random.PRNGKey(0), x[:8])
    specs = [tuple(jax.tree.leaves(tuple(leaf.sharding.spec)))
             for leaf in jax.tree.leaves(state.center_params)]
    assert any(WORKER_AXIS in s and TP_AXIS in s for s in specs), specs


def test_trainer_fsdp_kwarg_converges(toy_classification):
    """``fsdp=True`` alone (no tp_shards) routes to the GSPMD engine and
    trains to the same quality as the default path."""
    x, y, onehot = toy_classification
    df = from_numpy(x, onehot)
    t = dk.DOWNPOUR(FlaxModel(MLP(features=(32,), num_classes=2)),
                    loss="categorical_crossentropy",
                    worker_optimizer=("sgd", {"learning_rate": 0.1}),
                    num_workers=4, batch_size=16, num_epoch=8,
                    communication_window=4, fsdp=True)
    trained = t.train(df)
    h = t.get_history()["loss"]
    assert h[-1] < h[0] * 0.6
    preds = np.argmax(trained.predict(x), -1)
    assert np.mean(preds == np.argmax(onehot, -1)) > 0.8


def test_fsdp_virtual_workers():
    """More logical workers than devices (parallelism_factor) with a
    ZeRO-sharded center: 16 logical on the 8-device mesh compute the same
    trajectory as 16 plain data-parallel workers."""
    x, y, onehot = _data(n=512)
    adapter = lambda: FlaxModel(MLP(features=(32,), num_classes=4))
    xs, ys = _epoch_arrays(x, onehot, num_workers=16, n_windows=1, window=4, batch=8)

    fs = GSPMDEngine(adapter(), "categorical_crossentropy", "sgd", Downpour(4),
                     num_workers=16, fsdp=True, metrics=())
    assert fs.virtual == 2  # over-partitioning actually engaged (16 on 8)
    dp = WindowedEngine(adapter(), "categorical_crossentropy", "sgd", Downpour(4),
                        num_workers=16, metrics=())
    p_fs, loss_fs = _run(fs, xs, ys, x[:8], epochs=1)
    p_dp, loss_dp = _run(dp, xs, ys, x[:8], epochs=1)
    _assert_trees_close(p_dp, p_fs)
    np.testing.assert_allclose(loss_dp, loss_fs, rtol=2e-5, atol=2e-6)


def test_fsdp_staleness_schedule():
    """The per-step masked-commit (staleness simulation) body also runs with
    a sharded center: DynSGD under a skewed commit schedule stays finite."""
    x, y, onehot = _data()
    xs = x[:256].reshape(4, 16, 4, -1)  # [workers, steps, batch, d]
    ys = np.argmax(onehot[:256], -1).reshape(4, 16, 4).astype(np.int32)
    engine = GSPMDEngine(
        FlaxModel(MLP(features=(32,), num_classes=4)),
        "categorical_crossentropy", ("sgd", {"learning_rate": 0.05}),
        DynSGD(communication_window=4), num_workers=4, fsdp=True, metrics=(),
        commit_schedule=np.array([2, 4, 8, 16]),
    )
    state = engine.init_state(jax.random.PRNGKey(0), x[:4])
    sxs, sys_ = engine.shard_batches(xs, ys)
    state, stats = engine.run_epoch(state, sxs, sys_)
    assert np.isfinite(np.asarray(stats["loss"])).all()


def test_fsdp_composes_with_streaming(toy_classification):
    """The streaming window iterator drives the GSPMD engine with a sharded
    center exactly as it drives the shard_map engine: same trained params
    as the in-memory fsdp run."""
    x, y, onehot = toy_classification
    df = from_numpy(x, onehot)

    def train(streaming):
        t = dk.DOWNPOUR(FlaxModel(MLP(features=(32,), num_classes=2)),
                        loss="categorical_crossentropy",
                        worker_optimizer=("sgd", {"learning_rate": 0.1}),
                        num_workers=4, batch_size=16, num_epoch=2,
                        communication_window=4, seed=5, fsdp=True,
                        streaming=streaming)
        return t.train(df)

    a, b = train(False), train(True)
    flat_a, flat_b = jax.tree.leaves(a.params), jax.tree.leaves(b.params)
    assert len(flat_a) == len(flat_b)
    for pa, pb in zip(flat_a, flat_b):
        np.testing.assert_array_equal(np.asarray(pa), np.asarray(pb))


def test_fsdp_rejects_bad_combos():
    x, _, onehot = _data()
    # Every fsdp pair is SUPPORTED now (x sp: tests/test_fsdp_sp.py,
    # x pp: tests/test_pp_fsdp.py) and so is pipeline x seq
    # (tests/test_pp_sp.py) — but the latter needs a ring-attention staged
    # adapter; an MLP through pipeline+seq must still fail loudly.
    with pytest.raises(ValueError, match="staged adapter"):
        dk.DOWNPOUR(FlaxModel(MLP()), num_workers=4, seq_shards=2,
                    pipeline_stages=2).train(from_numpy(x, onehot))
