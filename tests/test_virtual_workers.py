"""Virtual workers: more logical workers than devices (the reference's
Spark-style over-partitioning, expressed as vmap over a per-device worker
dimension with collectives spanning both axes)."""

import jax
import numpy as np

import distkeras_tpu as dk
from distkeras_tpu.frame import from_numpy
from distkeras_tpu.models import MLP, FlaxModel
from distkeras_tpu.parallel.engine import plan_workers


def test_plan_workers_tiling():
    assert plan_workers(8, 8) == (8, 1)
    assert plan_workers(16, 8) == (8, 2)
    assert plan_workers(12, 8) == (6, 2)
    assert plan_workers(3, 8) == (3, 1)
    assert plan_workers(1, 8) == (1, 1)
    assert plan_workers(7, 4) == (1, 7)  # prime > devices: all virtual


def test_downpour_sixteen_workers_on_eight_devices(toy_classification):
    x, y, onehot = toy_classification
    df = from_numpy(x, onehot)
    t = dk.DOWNPOUR(FlaxModel(MLP(features=(16,), num_classes=2)),
                    loss="categorical_crossentropy",
                    worker_optimizer=("sgd", {"learning_rate": 0.1}),
                    num_workers=16, batch_size=8, num_epoch=8,
                    communication_window=2)
    trained = t.train(df)
    preds = trained.predict(x)
    acc = float(np.mean(np.argmax(preds, -1) == y))
    assert acc > 0.85
    # every logical worker committed every window: updates = workers * windows * epochs
    assert t.num_updates % 16 == 0 and t.num_updates > 0


def test_ensemble_more_models_than_devices(toy_classification):
    x, y, onehot = toy_classification
    df = from_numpy(x, onehot)
    t = dk.EnsembleTrainer(FlaxModel(MLP(features=(8,), num_classes=2)),
                           loss="categorical_crossentropy",
                           worker_optimizer=("sgd", {"learning_rate": 0.1}),
                           num_models=10, batch_size=8, num_epoch=4)
    models = t.train(df)
    assert len(models) == 10
    p0 = jax.tree.leaves(models[0].params)[0]
    p9 = jax.tree.leaves(models[9].params)[0]
    assert not np.allclose(p0, p9)


def test_tiling_invariance_of_center(toy_classification):
    """The center result must not depend on how logical workers tile onto
    devices: 8 workers as 8x1 vs forced 2x4 give identical centers."""
    from distkeras_tpu.algorithms import Downpour
    from distkeras_tpu.parallel.engine import WindowedEngine
    from distkeras_tpu.parallel.mesh import make_mesh
    from distkeras_tpu.models import as_adapter

    x, y, onehot = toy_classification
    adapter = as_adapter(MLP(features=(8,), num_classes=2))

    def run(mesh):
        engine = WindowedEngine(
            adapter, "categorical_crossentropy", ("sgd", {"learning_rate": 0.05}),
            Downpour(communication_window=2), num_workers=8, mesh=mesh,
        )
        state = engine.init_state(jax.random.PRNGKey(3), x[:8])
        xs = x[:512].reshape(8, 2, 2, 16, 8)
        ys = onehot[:512].reshape(8, 2, 2, 16, 2)
        xs, ys = engine.shard_batches(xs, ys)
        state, _ = engine.run_epoch(state, xs, ys)
        return jax.tree.map(np.asarray, state.center_params)

    full = run(make_mesh(8))     # 8 devices x 1 virtual
    tiled = run(make_mesh(2))    # 2 devices x 4 virtual
    for a, b in zip(jax.tree.leaves(full), jax.tree.leaves(tiled)):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_parallelism_factor_overpartitions(toy_classification):
    """Reference parity: parallelism_factor multiplies logical workers."""
    x, y, onehot = toy_classification
    df = from_numpy(x, onehot)
    t = dk.DOWNPOUR(FlaxModel(MLP(features=(8,), num_classes=2)),
                    loss="categorical_crossentropy",
                    worker_optimizer=("sgd", {"learning_rate": 0.1}),
                    num_workers=4, parallelism_factor=3, batch_size=8,
                    num_epoch=4, communication_window=2)
    trained = t.train(df)
    preds = trained.predict(x)
    acc = float(np.mean(np.argmax(preds, -1) == y))
    assert acc > 0.8
    # 12 logical workers all commit: update counter is a multiple of 12
    assert t.num_updates % 12 == 0 and t.num_updates > 0
