"""Worker script for the multi-host integration test.

Launched as N separate processes by test_multihost.py; each joins the
jax.distributed coordination service (the reference's master host:port
handshake), contributes 4 faked CPU devices, and runs DOWNPOUR over the
global 8-device mesh — commits ride the cross-process collective path (the
DCN analogue).
"""

import sys


def main(coordinator: str, num_processes: int, process_id: int) -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 4)
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    assert jax.device_count() == 4 * num_processes, jax.device_count()
    assert jax.local_device_count() == 4

    import numpy as np

    from distkeras_tpu.algorithms import Downpour
    from distkeras_tpu.models import MLP, FlaxModel
    from distkeras_tpu.parallel.engine import WindowedEngine

    engine = WindowedEngine(
        FlaxModel(MLP(features=(16,), num_classes=2)),
        "categorical_crossentropy",
        ("sgd", {"learning_rate": 0.1}),
        Downpour(communication_window=2),
        num_workers=jax.device_count(),
    )
    rng = np.random.default_rng(0)  # same data on every process (SPMD)
    x = rng.normal(size=(512, 8)).astype(np.float32)
    y = (x @ rng.normal(size=(8,)) > 0).astype(np.int32)
    onehot = np.eye(2, dtype=np.float32)[y]
    xs = x.reshape(8, 2, 2, 16, 8)
    ys = onehot.reshape(8, 2, 2, 16, 2)

    state = engine.init_state(jax.random.PRNGKey(0), x[:16])
    xs_d, ys_d = engine.shard_batches(xs, ys)
    losses = []
    for _ in range(6):
        state, stats = engine.run_epoch(state, xs_d, ys_d)
        losses.append(float(np.mean(np.asarray(stats["loss"]))))
    assert losses[-1] < losses[0], losses
    assert int(np.asarray(state.center_rule["num_updates"])) == 8 * 2 * 6
    print(f"process {process_id}: ok, losses {losses[0]:.3f}->{losses[-1]:.3f}")
    jax.distributed.shutdown()


if __name__ == "__main__":
    main(sys.argv[1], int(sys.argv[2]), int(sys.argv[3]))
