"""Worker script for the multi-host integration test.

Launched as N separate processes by test_multihost.py; each joins the
jax.distributed coordination service (the reference's master host:port
handshake), contributes its faked CPU devices, and trains DOWNPOUR over the
global 8-device mesh — commits ride the cross-process collective path (the
DCN analogue).  ``engine=windowed`` runs the shard_map engine over a 1-D
workers mesh; ``engine=gspmd`` runs the pjit engine over a 2-D
(workers, model) mesh, so tensor-parallel sharding propagation is exercised
across process boundaries too; ``engine=fsdp`` stores the center variable
ZeRO-3-sharded over a workers axis spanning both processes.
"""

import sys


def main(coordinator: str, num_processes: int, process_id: int,
         engine_kind: str = "windowed") -> None:
    import jax

    devices_per_proc = 8 // num_processes
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", devices_per_proc)
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    assert jax.device_count() == 8, jax.device_count()
    assert jax.local_device_count() == devices_per_proc

    import numpy as np

    from distkeras_tpu.algorithms import Downpour
    from distkeras_tpu.models import MLP, FlaxModel

    if engine_kind == "pipeline":
        from distkeras_tpu.models import StagedTransformer
        from distkeras_tpu.parallel.pipeline import PipelineEngine

        num_workers = 4  # (workers=4, stages=2) grid over the 8 devices
        adapter = StagedTransformer(
            vocab_size=50, num_classes=2, dim=16, heads=2,
            num_stages=2, blocks_per_stage=1, max_len=16,
        )
        # Stage-major device order: row-major reshape to (workers=4,
        # stages=2) then places stage 0 on devices 0-3 (process 0) and
        # stage 1 on devices 4-7 (process 1), so EVERY ppermute stage hop
        # crosses the process boundary.  The default id order would put
        # each worker's stage pair inside one process and the pipeline
        # axis would never touch the wire.
        devs = sorted(jax.devices(), key=lambda d: d.id)
        stage_major = [devs[w + s * num_workers]
                       for w in range(num_workers) for s in range(2)]
        engine = PipelineEngine(
            adapter,
            "categorical_crossentropy",
            ("sgd", {"learning_rate": 0.05}),
            Downpour(communication_window=2),
            num_workers=num_workers,
            microbatches=2,
            devices=stage_major,
        )
        stages_of = {d.process_index for d in engine.mesh.devices[0]}
        assert len(stages_of) == num_processes, (
            f"stage axis does not span processes: {stages_of}"
        )
    elif engine_kind == "gspmd":
        from distkeras_tpu.parallel.gspmd import GSPMDEngine

        num_workers = 4  # (workers=4, model=2) grid over the 8 devices
        engine = GSPMDEngine(
            FlaxModel(MLP(features=(16,), num_classes=2)),
            "categorical_crossentropy",
            ("sgd", {"learning_rate": 0.1}),
            Downpour(communication_window=2),
            num_workers=num_workers,
            tp_shards=2,
        )
    elif engine_kind == "fsdp":
        # ZeRO-3 center sharding over a workers axis that SPANS the process
        # boundary: each process stores only its slice of the center
        # variable, and the partitioner's gather-at-pull / scatter-at-commit
        # ride the cross-process (DCN-analogue) wire.
        from distkeras_tpu.parallel.gspmd import GSPMDEngine

        num_workers = 8
        engine = GSPMDEngine(
            FlaxModel(MLP(features=(16,), num_classes=2)),
            "categorical_crossentropy",
            ("sgd", {"learning_rate": 0.1}),
            Downpour(communication_window=2),
            num_workers=num_workers,
            fsdp=True,
        )
    else:  # "windowed" per-epoch dispatch, or "epochs" single-dispatch
        from distkeras_tpu.parallel.engine import WindowedEngine

        num_workers = 8
        engine = WindowedEngine(
            FlaxModel(MLP(features=(16,), num_classes=2)),
            "categorical_crossentropy",
            ("sgd", {"learning_rate": 0.1}),
            Downpour(communication_window=2),
            num_workers=num_workers,
        )

    rng = np.random.default_rng(0)  # same data on every process (SPMD)
    if engine_kind == "pipeline":
        # token-classification data for the staged transformer: the ppermute
        # pipeline hops (and the stage-sharded param residency) cross the
        # process boundary — the DCN analogue of the reference's workers
        # living on different cluster machines
        x = rng.integers(0, 50, size=(512, 16)).astype(np.int32)
        y = ((x == 7).sum(1) > (x == 3).sum(1)).astype(np.int32)
    else:
        x = rng.normal(size=(512, 8)).astype(np.float32)
        y = (x @ rng.normal(size=(8,)) > 0).astype(np.int32)
    onehot = np.eye(2, dtype=np.float32)[y]
    batch = 512 // (num_workers * 2 * 2)
    xs = x.reshape(num_workers, 2, 2, batch, -1)
    ys = onehot.reshape(num_workers, 2, 2, batch, 2)

    state = engine.init_state(jax.random.PRNGKey(0), x[:16])
    if engine_kind == "fsdp":
        # the sharded center must actually span processes: some leaf's
        # shards live on devices owned by different process indices
        spans = any(
            len({d.process_index for d in leaf.sharding.device_set}) > 1
            and not leaf.sharding.is_fully_replicated
            for leaf in jax.tree.leaves(state.center_params)
        )
        assert spans, "no center leaf is sharded across processes"
    xs_d, ys_d = engine.shard_batches(xs, ys)
    if engine_kind == "epochs":
        # the bench harness's timed region — the multi-epoch single-dispatch
        # run_epochs program with on-device reshuffle — compiled and run
        # across processes (pod-day rehearsal: this is the program a real
        # 8x-host sweep times)
        state, stats = engine.run_epochs(state, xs_d, ys_d, 6, shuffle_seed=0)
        losses = list(np.asarray(stats["loss"]).reshape(6, -1).mean(axis=1))
    else:
        losses = []
        for _ in range(6):
            state, stats = engine.run_epoch(state, xs_d, ys_d)
            losses.append(float(np.mean(np.asarray(stats["loss"]))))
    assert losses[-1] < losses[0], losses
    assert int(np.asarray(state.center_rule["num_updates"])) == num_workers * 2 * 6
    print(f"process {process_id}: ok ({engine_kind}), "
          f"losses {losses[0]:.3f}->{losses[-1]:.3f}")
    jax.distributed.shutdown()


if __name__ == "__main__":
    main(sys.argv[1], int(sys.argv[2]), int(sys.argv[3]),
         sys.argv[4] if len(sys.argv) > 4 else "windowed")
