"""Worker script for the multi-host integration test.

Launched as N separate processes by test_multihost.py; each joins the
jax.distributed coordination service (the reference's master host:port
handshake), contributes its faked CPU devices, and trains DOWNPOUR over the
global 8-device mesh — commits ride the cross-process collective path (the
DCN analogue).  ``engine=windowed`` runs the shard_map engine over a 1-D
workers mesh; ``engine=gspmd`` runs the pjit engine over a 2-D
(workers, model) mesh, so tensor-parallel sharding propagation is exercised
across process boundaries too; ``engine=fsdp`` stores the center variable
ZeRO-3-sharded over a workers axis spanning both processes.
"""

import sys


def _elastic(mode: str, process_id: int, num_processes: int,
             ckpt_dir: str) -> None:
    """Datapipe elastic-resume rehearsal (two phases, separate invocations).

    ``elastic_save`` (2 processes): full trainer flow — streaming +
    PrefetchRing + mid-epoch block checkpoints — killed by a simulated
    preemption at block 3 of epoch 1, leaving a partial step with a
    DataState cursor on the shared checkpoint dir.  ``elastic_resume``
    (4 processes): a fresh trainer at a DIFFERENT host topology (same
    8-device global mesh) restores model + DataState, replays the epoch's
    shuffle, skips the consumed blocks, and trains to completion.
    """
    import numpy as np

    import distkeras_tpu as dk
    from distkeras_tpu import checkpoint as ck
    from distkeras_tpu.datapipe import host_shard
    from distkeras_tpu.frame import from_numpy
    from distkeras_tpu.models import MLP, FlaxModel

    # the per-host sharding helper under a REAL multi-process runtime:
    # defaults pick up jax.process_index(), ranges partition the rows
    spans = [host_shard(512, i, num_processes) for i in range(num_processes)]
    assert host_shard(512) == spans[process_id]
    assert spans[0][0] == 0 and spans[-1][1] == 512
    assert all(a[1] == b[0] for a, b in zip(spans, spans[1:]))

    rng = np.random.default_rng(0)  # same data on every process (SPMD)
    x = rng.normal(size=(512, 8)).astype(np.float32)
    y = (x @ rng.normal(size=(8,)) > 0).astype(np.int32)
    onehot = np.eye(2, dtype=np.float32)[y]
    df = from_numpy(x, onehot)

    def trainer(resume):
        return dk.DOWNPOUR(
            FlaxModel(MLP(features=(16,), num_classes=2)),
            loss="categorical_crossentropy",
            worker_optimizer=("sgd", {"learning_rate": 0.1}),
            num_workers=8, batch_size=8, num_epoch=3,
            communication_window=2, seed=3, streaming=True, prefetch=2,
            checkpoint_dir=ckpt_dir, checkpoint_blocks=2, resume=resume,
        )

    if mode == "elastic_save":
        # 4 blocks/epoch; die at block 3 of epoch 1 — after the cursor-2
        # partial save, before the boundary save
        import distkeras_tpu.data as data_mod

        orig_iter = data_mod.epoch_window_iter
        calls = {"n": 0}

        def killing_iter(*a, **kw):
            calls["n"] += 1
            inner = orig_iter(*a, **kw)
            if calls["n"] == 2:
                def gen():
                    for i, blk in enumerate(inner):
                        if i == 3:
                            raise RuntimeError("simulated preemption")
                        yield blk
                return gen()
            return inner

        data_mod.epoch_window_iter = killing_iter
        died = False
        try:
            trainer(resume=False).train(df, shuffle=True)
        except RuntimeError as e:
            assert "preemption" in str(e)
            died = True
        assert died, "simulated preemption did not fire"
        data_mod.epoch_window_iter = orig_iter
        ck.wait_until_finished()  # commit the in-flight partial before exit
        ds = ck.restore_data_state(ckpt_dir)
        assert ds is not None and (ds.epoch, ds.block_cursor) == (1, 2), ds
    else:
        ds = ck.restore_data_state(ckpt_dir)
        assert ds is not None and (ds.epoch, ds.block_cursor) == (1, 2), ds
        t = trainer(resume=True)
        trained = t.train(df, shuffle=True)
        # resumed inside epoch 1: only epochs 1 and 2 ran here
        assert len(t.get_history()["loss"]) == 2, t.get_history()
        assert ck.latest_step(ckpt_dir) == 3
        # boundary saves supersede the mid-epoch cursor: the final sidecar
        # is a cursor-0 one carrying the next epoch's RNG bits
        final = ck.restore_data_state(ckpt_dir)
        assert final is None or int(final.block_cursor) == 0, final
        preds = np.argmax(np.asarray(trained.predict(x)), -1)
        acc = float((preds == y).mean())
        assert acc > 0.8, acc


def main(coordinator: str, num_processes: int, process_id: int,
         engine_kind: str = "windowed", ckpt_dir: str = "") -> None:
    import os

    devices_per_proc = 8 // num_processes
    # set before the backend initialises; jax_num_cpu_devices is the
    # modern spelling, XLA_FLAGS the fallback for older installs
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={devices_per_proc}"
    )
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", devices_per_proc)
    except AttributeError:
        pass  # XLA_FLAGS above already forces the device count
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    assert jax.device_count() == 8, jax.device_count()
    assert jax.local_device_count() == devices_per_proc

    if engine_kind in ("elastic_save", "elastic_resume"):
        _elastic(engine_kind, process_id, num_processes, ckpt_dir)
        print(f"process {process_id}: ok ({engine_kind})")
        jax.distributed.shutdown()
        return

    import numpy as np

    from distkeras_tpu.algorithms import Downpour
    from distkeras_tpu.models import MLP, FlaxModel

    if engine_kind == "pipeline":
        from distkeras_tpu.models import StagedTransformer
        from distkeras_tpu.parallel.pipeline import PipelineEngine

        num_workers = 4  # (workers=4, stages=2) grid over the 8 devices
        adapter = StagedTransformer(
            vocab_size=50, num_classes=2, dim=16, heads=2,
            num_stages=2, blocks_per_stage=1, max_len=16,
        )
        # Stage-major device order: row-major reshape to (workers=4,
        # stages=2) then places stage 0 on devices 0-3 (process 0) and
        # stage 1 on devices 4-7 (process 1), so EVERY ppermute stage hop
        # crosses the process boundary.  The default id order would put
        # each worker's stage pair inside one process and the pipeline
        # axis would never touch the wire.
        devs = sorted(jax.devices(), key=lambda d: d.id)
        stage_major = [devs[w + s * num_workers]
                       for w in range(num_workers) for s in range(2)]
        engine = PipelineEngine(
            adapter,
            "categorical_crossentropy",
            ("sgd", {"learning_rate": 0.05}),
            Downpour(communication_window=2),
            num_workers=num_workers,
            microbatches=2,
            devices=stage_major,
        )
        stages_of = {d.process_index for d in engine.mesh.devices[0]}
        assert len(stages_of) == num_processes, (
            f"stage axis does not span processes: {stages_of}"
        )
    elif engine_kind == "gspmd":
        from distkeras_tpu.parallel.gspmd import GSPMDEngine

        num_workers = 4  # (workers=4, model=2) grid over the 8 devices
        engine = GSPMDEngine(
            FlaxModel(MLP(features=(16,), num_classes=2)),
            "categorical_crossentropy",
            ("sgd", {"learning_rate": 0.1}),
            Downpour(communication_window=2),
            num_workers=num_workers,
            tp_shards=2,
        )
    elif engine_kind == "fsdp":
        # ZeRO-3 center sharding over a workers axis that SPANS the process
        # boundary: each process stores only its slice of the center
        # variable, and the partitioner's gather-at-pull / scatter-at-commit
        # ride the cross-process (DCN-analogue) wire.
        from distkeras_tpu.parallel.gspmd import GSPMDEngine

        num_workers = 8
        engine = GSPMDEngine(
            FlaxModel(MLP(features=(16,), num_classes=2)),
            "categorical_crossentropy",
            ("sgd", {"learning_rate": 0.1}),
            Downpour(communication_window=2),
            num_workers=num_workers,
            fsdp=True,
        )
    else:  # "windowed" per-epoch dispatch, or "epochs" single-dispatch
        from distkeras_tpu.parallel.engine import WindowedEngine

        num_workers = 8
        engine = WindowedEngine(
            FlaxModel(MLP(features=(16,), num_classes=2)),
            "categorical_crossentropy",
            ("sgd", {"learning_rate": 0.1}),
            Downpour(communication_window=2),
            num_workers=num_workers,
        )

    rng = np.random.default_rng(0)  # same data on every process (SPMD)
    if engine_kind == "pipeline":
        # token-classification data for the staged transformer: the ppermute
        # pipeline hops (and the stage-sharded param residency) cross the
        # process boundary — the DCN analogue of the reference's workers
        # living on different cluster machines
        x = rng.integers(0, 50, size=(512, 16)).astype(np.int32)
        y = ((x == 7).sum(1) > (x == 3).sum(1)).astype(np.int32)
    else:
        x = rng.normal(size=(512, 8)).astype(np.float32)
        y = (x @ rng.normal(size=(8,)) > 0).astype(np.int32)
    onehot = np.eye(2, dtype=np.float32)[y]
    batch = 512 // (num_workers * 2 * 2)
    xs = x.reshape(num_workers, 2, 2, batch, -1)
    ys = onehot.reshape(num_workers, 2, 2, batch, 2)

    state = engine.init_state(jax.random.PRNGKey(0), x[:16])
    if engine_kind == "fsdp":
        # the sharded center must actually span processes: some leaf's
        # shards live on devices owned by different process indices
        spans = any(
            len({d.process_index for d in leaf.sharding.device_set}) > 1
            and not leaf.sharding.is_fully_replicated
            for leaf in jax.tree.leaves(state.center_params)
        )
        assert spans, "no center leaf is sharded across processes"
    xs_d, ys_d = engine.shard_batches(xs, ys)
    if engine_kind == "epochs":
        # the bench harness's timed region — the multi-epoch single-dispatch
        # run_epochs program with on-device reshuffle — compiled and run
        # across processes (pod-day rehearsal: this is the program a real
        # 8x-host sweep times)
        state, stats = engine.run_epochs(state, xs_d, ys_d, 6, shuffle_seed=0)
        losses = list(np.asarray(stats["loss"]).reshape(6, -1).mean(axis=1))
    else:
        losses = []
        for _ in range(6):
            state, stats = engine.run_epoch(state, xs_d, ys_d)
            losses.append(float(np.mean(np.asarray(stats["loss"]))))
    assert losses[-1] < losses[0], losses
    assert int(np.asarray(state.center_rule["num_updates"])) == num_workers * 2 * 6
    print(f"process {process_id}: ok ({engine_kind}), "
          f"losses {losses[0]:.3f}->{losses[-1]:.3f}")
    jax.distributed.shutdown()


if __name__ == "__main__":
    main(sys.argv[1], int(sys.argv[2]), int(sys.argv[3]),
         sys.argv[4] if len(sys.argv) > 4 else "windowed",
         sys.argv[5] if len(sys.argv) > 5 else "")
