"""bench.py must stay runnable: every config builds its engine, run_config
emits the driver's JSON schema, and the harness converts failures into one
parseable JSON line instead of a traceback (the round-1 regression).  Tiny
shapes on the faked CPU mesh — this is a smoke test, not a measurement."""

import json

import numpy as np

import bench


def test_every_config_builds_engine():
    for config in bench.CONFIGS:
        engine, batch, window, shape, int_data, classes = bench._engine_for(config)
        assert engine.num_workers >= 1
        assert batch > 0 and window > 0 and classes > 1


def test_run_config_schema(monkeypatch):
    # Shrink the measurement so it runs in seconds on CPU.
    engine, _, window, shape, int_data, classes = bench._engine_for("mnist_mlp_single")

    def tiny_engine_for(config, num_workers=None):
        return engine, 8, window, shape, int_data, classes

    monkeypatch.setattr(bench, "_engine_for", tiny_engine_for)
    out = bench.run_config("mnist_mlp_single", n_windows=1, reps=1, k=2)
    required = {"metric", "value", "unit", "vs_baseline", "spread_pct",
                "mfu", "mfu_xla", "chips", "protocol"}
    assert required <= set(out), out.keys()
    assert out["unit"] == "samples/sec/chip"
    assert out["value"] > 0
    assert out["spread_pct"] >= 0
    assert out["chips"] >= 1
    assert out["protocol"] == bench.PROTOCOL
    assert out["mfu"] is None  # CPU backend: no peak-FLOPs table entry
    # the record must say where it ran and where the wall time went
    assert out["platform"] == "cpu"
    assert out["device_kind"]
    assert set(out["phases"]) == {"data", "h2d", "step", "commit"}
    assert all(v >= 0 for v in out["phases"].values())
    assert out["phases"]["data"] > 0 and out["phases"]["step"] > 0
    assert "platform_fallback" not in out  # no fallback happened here
    json.dumps(out)  # driver requires one JSON line


def test_run_config_records_dynamics_gauges(monkeypatch):
    """DISTKERAS_DYNAMICS=1 bench run: the health summary rides in the
    emitted record next to "phases" and lands in the metrics registry."""
    from distkeras_tpu import telemetry

    telemetry.dynamics.configure(enabled=True, watchdog="off")
    try:
        engine, _, window, shape, int_data, classes = bench._engine_for(
            "mnist_mlp_single")
        monkeypatch.setattr(
            bench, "_engine_for",
            lambda config, num_workers=None:
            (engine, 8, window, shape, int_data, classes))
        out = bench.run_config("mnist_mlp_single", n_windows=1, reps=1, k=1)
    finally:
        telemetry.dynamics.configure()
    dyn = out["dynamics"]
    assert dyn["grad_norm"] > 0
    assert "update_norm" in dyn and "divergence_max" in dyn
    assert dyn["nonfinite_grads_max"] == 0
    assert telemetry.metrics.snapshot()["dynamics_grad_norm"]["value"] > 0
    json.dumps(out)  # still one JSON line for the driver


def test_vs_baseline_null_when_unpinned(monkeypatch, tmp_path):
    engine, _, window, shape, int_data, classes = bench._engine_for("mnist_mlp_single")
    monkeypatch.setattr(
        bench, "_engine_for",
        lambda config, num_workers=None: (engine, 8, window, shape, int_data, classes),
    )
    empty = tmp_path / "pins.json"
    empty.write_text(json.dumps({"configs": {}}))
    monkeypatch.setattr(bench, "BASELINE_FILE", str(empty))
    out = bench.run_config("mnist_mlp_single", n_windows=1, reps=1, k=1)
    assert out["vs_baseline"] is None  # not 1.0: unpinned must be distinguishable


def test_baseline_file_pins_every_config():
    pins = json.load(open(bench.BASELINE_FILE))
    assert isinstance(pins.get("configs"), dict)
    assert all(isinstance(v, (int, float)) for v in pins["configs"].values())
    assert bench.HEADLINE in pins["configs"], "headline config must be pinned"
    missing = [c for c in bench.CONFIGS if c not in pins["configs"]]
    assert not missing, f"every config must carry a real-TPU pin: {missing}"
    # VERDICT r3 weak #1: pins are only a regression signal under the
    # protocol they were measured with — the file must say which, and it
    # must be the harness's current one.
    assert pins.get("protocol") == bench.PROTOCOL, (
        f"pin protocol {pins.get('protocol')!r} != harness {bench.PROTOCOL!r}"
        " — re-pin with `python bench.py --config all --write-baseline`"
    )


def test_vs_baseline_refuses_cross_protocol_pins(monkeypatch, tmp_path):
    stale = tmp_path / "pins.json"
    stale.write_text(json.dumps({
        "protocol": "some-older-protocol/v1",
        "configs": {"mnist_mlp_single": 100.0},
    }))
    monkeypatch.setattr(bench, "BASELINE_FILE", str(stale))
    out = bench._vs_baseline_fields("mnist_mlp_single", 630.0)
    assert out["vs_baseline"] is None  # NOT 6.3: that number would be a lie
    assert "re-pin" in out["pin_error"]
    fresh = tmp_path / "pins2.json"
    fresh.write_text(json.dumps({
        "protocol": bench.PROTOCOL,
        "configs": {"mnist_mlp_single": 100.0},
    }))
    monkeypatch.setattr(bench, "BASELINE_FILE", str(fresh))
    out = bench._vs_baseline_fields("mnist_mlp_single", 630.0)
    assert out["vs_baseline"] == 6.3 and "pin_error" not in out


def test_write_baseline_roundtrip(monkeypatch, tmp_path):
    import jax

    target = tmp_path / "pins.json"
    monkeypatch.setattr(bench, "BASELINE_FILE", str(target))
    live_kind = jax.devices()[0].device_kind
    bench.write_baseline({"_device_kind": live_kind,
                          "mnist_mlp_single": 123.4})
    data = json.load(open(target))
    assert data["protocol"] == bench.PROTOCOL
    assert data["configs"] == {"mnist_mlp_single": 123.4}
    assert data["device_kind"] == live_kind
    # and the comparison path accepts what write_baseline wrote
    out = bench._vs_baseline_fields("mnist_mlp_single", 123.4)
    assert out["vs_baseline"] == 1.0
    # ...but refuses a pin taken on different hardware (unit-error class)
    data["device_kind"] = "TPU imaginary9000"
    json.dump(data, open(target, "w"))
    out = bench._vs_baseline_fields("mnist_mlp_single", 123.4)
    assert out["vs_baseline"] is None and "pin_error" in out


def test_calibration_path_runs_and_clears_programs(monkeypatch):
    # reps=None exercises the two-point calibration: it must produce a
    # sane rep count and leave ONLY the final timed program alive (a live
    # extra executable degrades steady-state TPU throughput — see
    # WindowedEngine.clear_program_cache).
    engine, _, window, shape, int_data, classes = bench._engine_for("mnist_mlp_single")
    monkeypatch.setattr(
        bench, "_engine_for",
        lambda config, num_workers=None: (engine, 8, window, shape, int_data, classes),
    )
    out = bench.run_config("mnist_mlp_single", n_windows=1, reps=None, k=1,
                           min_set_seconds=0.01)
    assert out["value"] > 0
    # the calibration programs (reps=1, reps=4) were evicted; only the final
    # multi-epoch program remains cached
    keys = list(engine._epoch_fns)
    assert len(keys) == 1 and keys[0][0] == "multi"


def test_analytic_flops_closed_form():
    # Hand-recomputed layer sums against the LAYER_SPECS table: any drift
    # between the model zoo and these formulas must be deliberate.
    fwd = lambda c: sum(bench._spec_fwd_flops(s) for s in bench.LAYER_SPECS[c])
    assert fwd("cifar_cnn_downpour") == (
        2 * 32 * 32 * 64 * 27 + 2 * 32 * 32 * 64 * 576
        + 2 * 16 * 16 * 128 * 576 + 2 * 16 * 16 * 128 * 1152
        + 2 * 8192 * 256 + 2 * 256 * 10
    )  # = 196,482,048
    assert fwd("mnist_mlp_single") == 2 * (784 * 500 + 500 * 250 + 250 * 125 + 125 * 10)
    assert fwd("mnist_cnn_downpour") == (
        2 * 28 * 28 * 32 * 9 + 2 * 14 * 14 * 64 * 288
        + 2 * 3136 * 128 + 2 * 128 * 10
    )
    assert fwd("imdb_textcnn_dynsgd") == 2 * 256 * 128 * 128 * (3 + 4 + 5) + 2 * 384 * 2
    # ResNet-20: ~81.6 MFLOPs forward (sanity band, exact value is the sum)
    assert 80e6 < fwd("cifar_resnet20_adag") < 83e6
    # bandwidth-bound specs carry no MACs but ARE in the table (the measured
    # ceiling pays their wall): embed for TextCNN, bn for ResNet-20
    kinds = {s[0] for s in bench.LAYER_SPECS["imdb_textcnn_dynsgd"]}
    assert "embed" in kinds
    kinds = {s[0] for s in bench.LAYER_SPECS["cifar_resnet20_adag"]}
    assert "bn" in kinds
    for config in bench.CONFIGS:
        assert bench.analytic_train_flops_per_sample(config) == 3.0 * fwd(config)


def test_layer_microbench_builds_every_spec_kind():
    """Each spec kind lowers to a runnable fwd+bwd program (tiny shapes —
    this is the machinery behind --mfu-ceiling, not a measurement)."""
    import jax

    for spec in [("conv", 4, 4, 8, 3, 3, 1), ("conv", 4, 4, 8, 3, 8, 2),
                 ("conv1d", 8, 8, 3, 8), ("dense", 16, 8),
                 ("embed", 50, 8, 12), ("bn", 4, 4, 8)]:
        p, x, fn = bench._layer_fwd_bwd(spec, batch=2, dtype=jax.numpy.float32)
        g = fn(p, x)
        gp = g[0] if isinstance(g, tuple) else g
        assert gp.shape == p.shape
        assert jax.numpy.isfinite(gp).all()  # dklint: disable=DK107


def test_layer_wall_descent_carry_stays_finite():
    """The chained-scan protocol's claim 'descent keeps the carried values
    bounded' must actually hold: with the sum-of-squares loss the larger
    dense specs diverged to NaN within 64 reps (review finding) — the mean
    loss keeps every spec's gradient inside the stability bound."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    p, x, fn = bench._layer_fwd_bwd(("dense", 8192, 256), batch=64,
                                    dtype=jnp.bfloat16)
    eps = jnp.asarray(1e-3, jnp.bfloat16)

    def body(carry, _):
        p, x = carry
        gp, gx = fn(p, x)
        return (p - eps * gp, x - eps * gx), None

    (p_out, x_out), _ = jax.jit(  # dklint: disable=DK102 — one-shot test
        lambda p, x: lax.scan(body, (p, x), None, length=64)
    )(p, x)
    assert jnp.isfinite(p_out.astype(jnp.float32)).all()
    assert jnp.isfinite(x_out.astype(jnp.float32)).all()


def test_layer_wall_chained_scan_measures_compute_not_dispatch():
    """The wall comes from k chained reps inside ONE compiled scan; it must
    be positive, finite, and far below the single-dispatch wall for a tiny
    layer (the r4 version measured per-dispatch overhead x layers, which on
    the tunnel produced 'ceilings' BELOW measured whole-model MFU)."""
    import jax

    w = bench._layer_wall_seconds(("dense", 32, 16), batch=4,
                                  dtype=jax.numpy.float32, min_time=0.02)
    assert 0 < w < 0.02, w  # per-rep wall, not the whole timed set


def test_mfu_ceiling_without_peak_table_entry(monkeypatch):
    # CPU device kind has no peak-FLOPs entry: the ceiling line must be a
    # parseable error verdict, not a crash
    out = bench.run_mfu_ceiling("mnist_mlp_single")
    assert out["metric"] == "mnist_mlp_single_mfu_ceiling"
    assert out["value"] is None and "error" in out
    json.dumps(out)


def test_mfu_withheld_when_crosscheck_disagrees():
    peak = 100e12
    sps = 1e5
    batch = 256
    analytic = bench.analytic_train_flops_per_sample("cifar_cnn_downpour")
    # Agreement (xla within 2x): mfu printed, cross-check alongside.
    ok = bench._mfu_fields("cifar_cnn_downpour", sps, batch, peak,
                           xla_step_flops=batch * analytic * 0.9)
    assert ok["mfu"] is not None and ok["mfu_xla"] is not None
    # Disagreement >2x (the round-2 scan-body undercount): mfu withheld,
    # both counts emitted for inspection.
    bad = bench._mfu_fields("cifar_cnn_downpour", sps, batch, peak,
                            xla_step_flops=batch * analytic / 140.0)
    assert bad["mfu"] is None
    assert bad["mfu_analytic"] is not None and bad["mfu_xla"] is not None
    # No cross-check available: the analytic number still stands (it is the
    # hand-derived one), with mfu_xla null.
    solo = bench._mfu_fields("cifar_cnn_downpour", sps, batch, peak, None)
    assert solo["mfu"] is not None and solo["mfu_xla"] is None


def test_run_streaming_schema(monkeypatch):
    engine, _, window, shape, int_data, classes = bench._engine_for("mnist_mlp_single")
    monkeypatch.setattr(
        bench, "_engine_for",
        lambda config, num_workers=None: (engine, 8, window, shape, int_data, classes),
    )
    out = bench.run_streaming("mnist_mlp_single", n_windows=2, reps=1, k=1)
    assert out["metric"] == "mnist_mlp_single_streaming_overhead"
    assert out["in_memory_samples_per_sec_per_chip"] > 0
    assert out["streaming_samples_per_sec_per_chip"] > 0
    assert out["value"] is not None and out["value"] < 1.0
    json.dumps(out)


def test_every_line_carries_an_at_a_glance_status(capsys, monkeypatch):
    """rc is always 0 by deadman design, so the verdict must live in the
    line itself: success lines say status=ok, error lines status=error —
    including results that return an error field through the normal path
    (the no-peak-table mfu ceiling; _peak_flops is pinned to None so the
    test is host-independent and never runs the real layer bench)."""
    assert json.loads(bench._ok_line({"metric": "m", "value": 1.0}))["status"] == "ok"
    monkeypatch.setattr(bench, "_peak_flops", lambda kind: None)
    ceiling = bench.run_mfu_ceiling("mnist_mlp_single")
    assert json.loads(bench._ok_line(ceiling))["status"] == "error"
    bench._emit_error("boom")
    assert json.loads(capsys.readouterr().out.strip())["status"] == "error"


def test_emit_error_is_parseable_json(capsys):
    bench._emit_error("TPU fell over")
    line = capsys.readouterr().out.strip()
    parsed = json.loads(line)
    assert parsed["metric"] == bench.HEADLINE_METRIC
    assert parsed["value"] is None and parsed["vs_baseline"] is None
    assert "TPU fell over" in parsed["error"]


def test_main_emits_json_line_when_even_cpu_fallback_fails(monkeypatch, capsys):
    # Both the real backend AND the CPU fallback probe fail: only then may
    # main() emit error verdicts (one parseable line per pending metric).
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.setattr(bench, "_PLATFORM_FALLBACK", None)
    monkeypatch.setattr(bench, "preflight", lambda **kw: {"error": "UNAVAILABLE: nope"})
    monkeypatch.setattr("sys.argv", ["bench.py"])
    bench.main()  # must not raise
    parsed = json.loads(capsys.readouterr().out.strip())
    assert parsed["value"] is None
    assert "UNAVAILABLE" in parsed["error"]
    assert "CPU fallback also failed" in parsed["error"]


def test_main_falls_back_to_cpu_smoke_when_backend_dies(monkeypatch, capsys):
    """Dead TPU tunnel at launch: instead of an all-error run, main() flips
    to a CPU mesh and measures smoke shapes — the emitted line is a real
    measurement carrying platform + phases, not an error verdict."""
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.setattr(bench, "_PLATFORM_FALLBACK", None)
    probes = []

    def flaky_preflight(**kw):
        probes.append(kw)
        if len(probes) == 1:
            return {"error": "UNAVAILABLE: tunnel died"}
        return {"n": 8, "platform": "cpu", "kind": "cpu"}

    seen_kw = {}

    def fake_run_config(config, **kw):
        seen_kw.update(kw)
        return {"metric": f"{config}_samples_per_sec_per_chip", "value": 42.0,
                "platform": "cpu", "phases": {}, "chips": 8,
                "platform_fallback": bench._PLATFORM_FALLBACK}

    monkeypatch.setattr(bench, "preflight", flaky_preflight)
    monkeypatch.setattr(bench, "run_config", fake_run_config)
    monkeypatch.setattr("sys.argv", ["bench.py"])
    bench.main()
    parsed = json.loads(capsys.readouterr().out.strip())
    assert parsed["status"] == "ok"
    assert parsed["value"] == 42.0
    assert "UNAVAILABLE" in parsed["platform_fallback"]
    # the fallback retried the probe exactly once and shrank the shapes to
    # the CPU smoke protocol
    assert len(probes) == 2 and probes[1] == {"max_tries": 1}
    assert seen_kw == dict(n_windows=1, reps=1, k=1, batch_override=16,
                           window_override=2)


def test_write_baseline_refused_on_cpu_smoke(monkeypatch, capsys, tmp_path):
    # A CPU smoke run must never pin regression baselines.
    monkeypatch.setattr(bench, "_PLATFORM_FALLBACK", None)
    monkeypatch.setattr(bench, "BASELINE_FILE", str(tmp_path / "pins.json"))
    monkeypatch.setattr(bench, "preflight",
                        lambda **kw: {"n": 8, "platform": "cpu", "kind": "cpu"})
    monkeypatch.setattr(
        bench, "run_config",
        lambda config, **kw: {"metric": "m", "value": 1.0})
    monkeypatch.setattr("sys.argv", ["bench.py", "--write-baseline"])
    bench.main()
    lines = [json.loads(l) for l in capsys.readouterr().out.strip().splitlines()]
    refusal = [l for l in lines if l.get("metric") == "write_baseline"]
    assert len(refusal) == 1
    assert "refused" in refusal[0]["error"]
    assert not (tmp_path / "pins.json").exists()


def test_main_emits_json_line_when_config_raises(monkeypatch, capsys):
    monkeypatch.setattr(bench, "preflight", lambda **kw: {"n": 1, "platform": "cpu", "kind": "cpu"})

    def boom(config, **kw):
        raise RuntimeError("compile exploded")

    monkeypatch.setattr(bench, "run_config", boom)
    monkeypatch.setattr("sys.argv", ["bench.py"])
    bench.main()
    parsed = json.loads(capsys.readouterr().out.strip())
    assert parsed["metric"] == bench.HEADLINE_METRIC
    assert "compile exploded" in parsed["error"]


def test_preflight_exhausted_timeouts_count_init_failures(monkeypatch):
    """Every failed probe lands in the bench_backend_init_failures counter,
    including the retries-exhausted/timeout branch — a fallback record must
    say HOW flaky the backend was."""
    from distkeras_tpu import telemetry

    telemetry.metrics.reset()
    monkeypatch.setattr(
        bench, "_probe_subprocess",
        lambda timeout: (False, "backend init timed out after 1s"))
    out = bench.preflight(max_tries=3, init_timeout=1, retry_sleep=0)
    assert "timed out" in out["error"]
    snap = telemetry.metrics.snapshot()
    assert snap["bench_backend_init_failures"]["value"] == 3.0
    telemetry.metrics.reset()


def test_ensure_backend_routes_timeout_through_cpu_fallback(monkeypatch):
    """The retries-exhausted/timeout branch takes the same CPU-smoke road as
    an UNAVAILABLE tunnel: ensure_backend records the reason and re-probes
    once on the CPU mesh instead of emitting error verdicts."""
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.setattr(bench, "_PLATFORM_FALLBACK", None)
    probes = []

    def timing_out_preflight(**kw):
        probes.append(kw)
        if len(probes) == 1:
            return {"error": "backend init timed out after 120s"}
        return {"n": 8, "platform": "cpu", "kind": "cpu"}

    monkeypatch.setattr(bench, "preflight", timing_out_preflight)
    backend = bench.ensure_backend(["m"])
    assert backend == {"n": 8, "platform": "cpu", "kind": "cpu"}
    assert "timed out" in bench._PLATFORM_FALLBACK
    assert probes == [{}, {"max_tries": 1}]


def test_ensure_backend_emits_error_per_pending_metric(monkeypatch, capsys):
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.setattr(bench, "_PLATFORM_FALLBACK", None)
    monkeypatch.setattr(bench, "preflight",
                        lambda **kw: {"error": "UNAVAILABLE: nope"})
    assert bench.ensure_backend(["m_a", "m_b"]) is None
    lines = [json.loads(l) for l in capsys.readouterr().out.strip().splitlines()]
    assert [l["metric"] for l in lines] == ["m_a", "m_b"]
    assert all("CPU fallback also failed" in l["error"] for l in lines)


def test_preflight_succeeds_after_live_probe(monkeypatch):
    # The child probe targets the default backend (TPU under the driver);
    # here it's stubbed live so preflight proceeds to the in-process init,
    # which conftest pins to the 8-device CPU mesh.
    monkeypatch.setattr(bench, "_probe_subprocess", lambda timeout: (True, ""))
    out = bench.preflight(init_timeout=60)
    assert out.get("n", 0) >= 1


def test_preflight_gives_up_on_nontransient_probe_failure(monkeypatch):
    calls = []

    def dead_probe(timeout):
        calls.append(timeout)
        return False, "NotFoundError: no such platform"

    monkeypatch.setattr(bench, "_probe_subprocess", dead_probe)
    out = bench.preflight(init_timeout=1, retry_sleep=0)
    assert "error" in out
    assert len(calls) == 1  # non-transient: no pointless retries


def test_preflight_retries_transient_unavailable(monkeypatch):
    calls = []

    def flaky_probe(timeout):
        calls.append(timeout)
        if len(calls) < 3:
            return False, "UNAVAILABLE: TPU backend setup/compile error"
        return True, ""

    monkeypatch.setattr(bench, "_probe_subprocess", flaky_probe)
    out = bench.preflight(init_timeout=60, retry_sleep=0)
    assert out.get("n", 0) >= 1
    assert len(calls) == 3


def test_scaling_sweep_schema(monkeypatch):
    calls = []

    def fake_run_config(config, num_workers=None, **kw):
        calls.append(num_workers)
        return {"value": 100.0 * (0.95 ** (num_workers or 1)),
                "chips": num_workers or 1}

    monkeypatch.setattr(bench, "run_config", fake_run_config)
    monkeypatch.setattr(bench, "_peak_flops", lambda kind: None)
    out = bench.run_scaling("cifar_cnn_downpour")
    assert out["metric"] == "cifar_cnn_downpour_scaling_efficiency"
    assert out["num_chips"] == max(calls)
    assert 0 < out["value"] <= 1.0
    assert set(out["points_samples_per_sec_per_chip"]) == {str(c) for c in calls}
    assert set(out["points_chips"]) == {str(c) for c in calls}
    assert out["num_processes"] == 1
    json.dumps(out)


def test_deadman_emits_pending_verdicts_and_exits():
    """Mid-run tunnel death (observed 2026-07-31: a sweep hung 50 min inside
    one config's compile): the deadman must turn a hang into one error JSON
    line per pending metric and exit rc 0 — the lines ARE the verdict."""
    import subprocess
    import sys

    code = (
        "import time, bench\n"
        "d = bench._Deadman()\n"
        "d.arm(0.2, ['m1', 'm2'])\n"
        "time.sleep(30)\n"  # simulated hung XLA call
        "print('never reached')\n"
    )
    import os

    root = os.path.dirname(os.path.abspath(bench.__file__))
    proc = subprocess.run([sys.executable, "-c", code], capture_output=True,
                          text=True, timeout=25, cwd=root)
    assert proc.returncode == 0
    lines = [json.loads(l) for l in proc.stdout.strip().splitlines()]
    assert [l["metric"] for l in lines] == ["m1", "m2"]
    assert all("hung mid-run" in l["error"] for l in lines)
    assert "never reached" not in proc.stdout


def test_deadman_disarm_cancels():
    """Subprocess like the sibling test: if disarm regresses, the stray
    timer os._exit(0)s the host process — in-process that would silently
    truncate the pytest run with rc 0."""
    import os
    import subprocess
    import sys

    code = (
        "import time, bench\n"
        "d = bench._Deadman()\n"
        "d.arm(0.05, ['m'])\n"
        "d.disarm()\n"
        "time.sleep(0.3)\n"
        "print('survived')\n"
    )
    root = os.path.dirname(os.path.abspath(bench.__file__))
    proc = subprocess.run([sys.executable, "-c", code], capture_output=True,
                          text=True, timeout=25, cwd=root)
    assert proc.returncode == 0
    assert "survived" in proc.stdout
    assert "hung mid-run" not in proc.stdout


def test_scaling_line_reads_error_when_a_point_fails(monkeypatch):
    """A pod sweep must not read green over a broken point: run_scaling's
    own contract (its in-loop comment) and _ok_line's at-a-glance verdict.
    Simulate a 2-process sweep where the k=2 point dies on the measuring
    process — the emitted line must carry status: error, not ok."""
    import jax
    from jax.experimental import multihost_utils

    def fake_run_config(config, num_workers=None, **kw):
        if num_workers and num_workers > 1:
            raise RuntimeError("device fault at k=%d" % num_workers)
        return {"value": 100.0, "chips": 1}

    joins = []
    monkeypatch.setattr(bench, "run_config", fake_run_config)
    monkeypatch.setattr(bench, "_join_reps_broadcast",
                        lambda: joins.append(1))
    monkeypatch.setattr(jax, "device_count", lambda: 2)
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(multihost_utils, "sync_global_devices",
                        lambda name: None)
    out = bench.run_scaling("mnist_mlp_single")
    assert out["point_errors"] == {"2": "RuntimeError: device fault at k=2"}
    line = json.loads(bench._ok_line(out))
    assert line["status"] == "error"
    assert "scaling point" in line["error"]
    # the pre-calibration failure joined the owners' global reps broadcast
    # (sub-mesh deadlock guard) exactly once
    assert joins == [1]
