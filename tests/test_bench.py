"""bench.py must stay runnable: every config builds its engine, run_config
emits the driver's JSON schema, and the harness converts failures into one
parseable JSON line instead of a traceback (the round-1 regression).  Tiny
shapes on the faked CPU mesh — this is a smoke test, not a measurement."""

import json

import numpy as np

import bench


def test_every_config_builds_engine():
    for config in bench.CONFIGS:
        engine, batch, window, shape, int_data, classes = bench._engine_for(config)
        assert engine.num_workers >= 1
        assert batch > 0 and window > 0 and classes > 1


def test_run_config_schema(monkeypatch):
    # Shrink the measurement so it runs in seconds on CPU.
    engine, _, window, shape, int_data, classes = bench._engine_for("mnist_mlp_single")

    def tiny_engine_for(config, num_workers=None):
        return engine, 8, window, shape, int_data, classes

    monkeypatch.setattr(bench, "_engine_for", tiny_engine_for)
    out = bench.run_config("mnist_mlp_single", n_windows=1, reps=1)
    assert set(out) == {"metric", "value", "unit", "vs_baseline", "mfu"}
    assert out["unit"] == "samples/sec/chip"
    assert out["value"] > 0
    assert out["mfu"] is None  # CPU backend: no peak-FLOPs table entry
    json.dumps(out)  # driver requires one JSON line


def test_vs_baseline_null_when_unpinned(monkeypatch, tmp_path):
    engine, _, window, shape, int_data, classes = bench._engine_for("mnist_mlp_single")
    monkeypatch.setattr(
        bench, "_engine_for",
        lambda config, num_workers=None: (engine, 8, window, shape, int_data, classes),
    )
    empty = tmp_path / "pins.json"
    empty.write_text(json.dumps({"configs": {}}))
    monkeypatch.setattr(bench, "BASELINE_FILE", str(empty))
    out = bench.run_config("mnist_mlp_single", n_windows=1, reps=1)
    assert out["vs_baseline"] is None  # not 1.0: unpinned must be distinguishable


def test_baseline_file_pins_every_config():
    pins = json.load(open(bench.BASELINE_FILE))
    assert isinstance(pins.get("configs"), dict)
    assert all(isinstance(v, (int, float)) for v in pins["configs"].values())
    assert bench.HEADLINE in pins["configs"], "headline config must be pinned"
    missing = [c for c in bench.CONFIGS if c not in pins["configs"]]
    if missing:
        # Pins require one bench run on real TPU hardware; until the next
        # window where the chip is reachable, unpinned configs report
        # vs_baseline null (tested above) rather than a fake 1.0.
        import pytest

        pytest.xfail(f"configs awaiting a real-TPU pin run: {missing}")


def test_emit_error_is_parseable_json(capsys):
    bench._emit_error("TPU fell over")
    line = capsys.readouterr().out.strip()
    parsed = json.loads(line)
    assert parsed["metric"] == bench.HEADLINE_METRIC
    assert parsed["value"] is None and parsed["vs_baseline"] is None
    assert "TPU fell over" in parsed["error"]


def test_main_emits_json_line_when_backend_unavailable(monkeypatch, capsys):
    monkeypatch.setattr(bench, "preflight", lambda **kw: {"error": "UNAVAILABLE: nope"})
    monkeypatch.setattr("sys.argv", ["bench.py"])
    bench.main()  # must not raise
    parsed = json.loads(capsys.readouterr().out.strip())
    assert parsed["value"] is None
    assert "UNAVAILABLE" in parsed["error"]


def test_main_emits_json_line_when_config_raises(monkeypatch, capsys):
    monkeypatch.setattr(bench, "preflight", lambda **kw: {"n": 1, "platform": "cpu", "kind": "cpu"})

    def boom(config, **kw):
        raise RuntimeError("compile exploded")

    monkeypatch.setattr(bench, "run_config", boom)
    monkeypatch.setattr("sys.argv", ["bench.py"])
    bench.main()
    parsed = json.loads(capsys.readouterr().out.strip())
    assert parsed["metric"] == bench.HEADLINE_METRIC
    assert "compile exploded" in parsed["error"]


def test_preflight_succeeds_after_live_probe(monkeypatch):
    # The child probe targets the default backend (TPU under the driver);
    # here it's stubbed live so preflight proceeds to the in-process init,
    # which conftest pins to the 8-device CPU mesh.
    monkeypatch.setattr(bench, "_probe_subprocess", lambda timeout: (True, ""))
    out = bench.preflight(init_timeout=60)
    assert out.get("n", 0) >= 1


def test_preflight_gives_up_on_nontransient_probe_failure(monkeypatch):
    calls = []

    def dead_probe(timeout):
        calls.append(timeout)
        return False, "NotFoundError: no such platform"

    monkeypatch.setattr(bench, "_probe_subprocess", dead_probe)
    out = bench.preflight(init_timeout=1, retry_sleep=0)
    assert "error" in out
    assert len(calls) == 1  # non-transient: no pointless retries


def test_preflight_retries_transient_unavailable(monkeypatch):
    calls = []

    def flaky_probe(timeout):
        calls.append(timeout)
        if len(calls) < 3:
            return False, "UNAVAILABLE: TPU backend setup/compile error"
        return True, ""

    monkeypatch.setattr(bench, "_probe_subprocess", flaky_probe)
    out = bench.preflight(init_timeout=60, retry_sleep=0)
    assert out.get("n", 0) >= 1
    assert len(calls) == 3


def test_scaling_sweep_schema(monkeypatch):
    calls = []

    def fake_run_config(config, num_workers=None, **kw):
        calls.append(num_workers)
        return {"value": 100.0 * (0.95 ** (num_workers or 1))}

    monkeypatch.setattr(bench, "run_config", fake_run_config)
    monkeypatch.setattr(bench, "_peak_flops", lambda kind: None)
    out = bench.run_scaling("cifar_cnn_downpour")
    assert out["metric"] == "cifar_cnn_downpour_scaling_efficiency"
    assert out["num_chips"] == max(calls)
    assert 0 < out["value"] <= 1.0
    assert set(out["points_samples_per_sec_per_chip"]) == {str(c) for c in calls}
    json.dumps(out)
