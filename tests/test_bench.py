"""bench.py must stay runnable: every config builds its engine, and
run_config emits the driver's JSON schema.  Tiny shapes on the faked CPU
mesh — this is a smoke test, not a measurement."""

import json

import numpy as np

import bench


def test_every_config_builds_engine():
    for config in [
        "cifar_cnn_downpour", "mnist_mlp_single", "mnist_cnn_downpour",
        "cifar_cnn_aeasgd", "cifar_resnet20_adag", "imdb_textcnn_dynsgd",
    ]:
        engine, batch, window, shape, int_data, classes = bench._engine_for(config)
        assert engine.num_workers >= 1
        assert batch > 0 and window > 0 and classes > 1


def test_run_config_schema(monkeypatch):
    # Shrink the measurement so it runs in seconds on CPU.
    import jax

    engine, _, window, shape, int_data, classes = bench._engine_for("mnist_mlp_single")

    def tiny_engine_for(config):
        return engine, 8, window, shape, int_data, classes

    monkeypatch.setattr(bench, "_engine_for", tiny_engine_for)
    out = bench.run_config("mnist_mlp_single", n_windows=1, reps=1)
    assert set(out) == {"metric", "value", "unit", "vs_baseline"}
    assert out["unit"] == "samples/sec/chip"
    assert out["value"] > 0
    json.dumps(out)  # driver requires one JSON line


def test_baseline_file_schema():
    pins = json.load(open(bench.BASELINE_FILE))
    assert isinstance(pins.get("configs"), dict)
    assert all(isinstance(v, (int, float)) for v in pins["configs"].values())
