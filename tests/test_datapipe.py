"""distkeras_tpu.datapipe — the sharded, prefetching, resumable input
pipeline (ISSUE 10 tentpole).

Pins the subsystem's four guarantees:

* **Bitwise parity** — blocks through the PrefetchRing, and whole training
  trajectories with ``prefetch>0``, are identical to the non-prefetched path
  (float32 AND the fused-bf16 host gather+cast).
* **Deterministic resume** — a run killed mid-epoch restores model +
  DataState, consumes exactly the remaining blocks of the interrupted epoch,
  and lands on the uninterrupted run's final params bit-for-bit.
* **Packing correctness** — packed segment-ID attention produces, for every
  packed segment, the logits the sequence gets alone (TransformerLM and
  StagedLM).
* **No hangs, no orphans** — producer exceptions propagate, close() always
  joins the worker thread, and the stall/depth metrics + gather spans make
  the overlap observable.
"""

import os
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distkeras_tpu import telemetry
from distkeras_tpu.data import epoch_window_iter
from distkeras_tpu.datapipe import (
    ArraySource,
    DataState,
    MemmapSource,
    PrefetchRing,
    host_shard,
    pack_sequences,
)


@pytest.fixture
def live_telemetry(tmp_path, monkeypatch):
    """Telemetry on with clean global tracer/registry, flushes to tmp."""
    monkeypatch.setenv("DISTKERAS_TELEMETRY_DIR", str(tmp_path))
    telemetry.configure(True)
    telemetry.trace.reset()
    telemetry.metrics.reset()
    yield
    telemetry.trace.reset()
    telemetry.metrics.reset()
    telemetry.configure(None)


def _toy_blocks(seed=1, n=64, workers=2, batch=4, window=2, **kw):
    feats = np.random.default_rng(0).normal(size=(n, 4)).astype(np.float32)
    labels = (np.arange(n) % 3).astype(np.int32)
    rng = np.random.default_rng(seed) if seed is not None else None
    return epoch_window_iter(feats, labels, workers, batch, window,
                             rng=rng, **kw)


# ------------------------------------------------------------------- ring

def test_ring_blocks_bitwise_identical():
    plain = list(_toy_blocks(seed=1))
    ring = list(PrefetchRing(_toy_blocks(seed=1), depth=2))
    assert len(ring) == len(plain) > 0
    for (a, b), (c, d) in zip(plain, ring):
        assert a.tobytes() == c.tobytes()
        assert b.tobytes() == d.tobytes()


def _no_prefetch_threads():
    return not any(t.name == "datapipe-prefetch" and t.is_alive()
                   for t in threading.enumerate())


def test_ring_producer_exception_propagates_without_orphan():
    first = next(_toy_blocks())

    def bad():
        yield first
        raise RuntimeError("boom")

    ring = PrefetchRing(bad(), depth=2)
    got = next(ring)
    assert got[0].tobytes() == first[0].tobytes()
    with pytest.raises(RuntimeError, match="boom"):
        next(ring)
    # the producer thread is joined by the time the exception surfaces
    assert _no_prefetch_threads()
    # and the ring is terminal, not wedged
    with pytest.raises(StopIteration):
        next(ring)


def test_ring_close_mid_stream_joins_producer():
    ring = PrefetchRing(_toy_blocks(), depth=1)
    next(ring)
    ring.close()
    assert _no_prefetch_threads()
    ring.close()  # idempotent
    with pytest.raises(StopIteration):
        next(ring)


def test_engine_error_path_closes_ring(toy_classification):
    """run_epoch_streaming's try/finally must close the ring on ANY exit —
    here the producer's own error surfaces through the engine and the
    worker thread is still joined (no orphan to leak into the next test)."""
    from distkeras_tpu.algorithms import Downpour
    from distkeras_tpu.models import MLP, FlaxModel
    from distkeras_tpu.parallel.engine import WindowedEngine

    x, y, onehot = toy_classification
    eng = WindowedEngine(
        FlaxModel(MLP(features=(16,), num_classes=2)),
        loss="categorical_crossentropy",
        worker_optimizer=("sgd", {"learning_rate": 0.05}),
        rule=Downpour(communication_window=2),
        num_workers=4,
    )
    state = eng.init_state(jax.random.PRNGKey(0), x[:8])
    blocks = list(epoch_window_iter(x, onehot, 4, 8, 2))

    def dying_source():
        yield blocks[0]
        yield blocks[1]
        raise RuntimeError("source died")

    ring = PrefetchRing(dying_source(), depth=2)
    with pytest.raises(RuntimeError, match="source died"):
        eng.run_epoch_streaming(state, ring)
    assert _no_prefetch_threads()
    assert ring._closed.is_set()


class _SlowBlocks:
    def __init__(self, blocks, latency):
        self._blocks, self._latency = blocks, latency

    def __iter__(self):
        for b in self._blocks:
            time.sleep(self._latency)
            yield b


def test_ring_stall_metrics_and_link_warning(live_telemetry, toy_classification):
    """A throttled source through the ring: the consumer's waits land in
    ``datapipe_stall_seconds``, the depth gauge appears, and the engine's
    link-bound guardrail still fires — the ring hides latency, it must not
    hide the verdict that the source is the bottleneck."""
    from distkeras_tpu.algorithms import Downpour
    from distkeras_tpu.models import MLP, FlaxModel
    from distkeras_tpu.parallel.engine import WindowedEngine

    x, y, onehot = toy_classification
    eng = WindowedEngine(
        FlaxModel(MLP(features=(16,), num_classes=2)),
        loss="categorical_crossentropy",
        worker_optimizer=("sgd", {"learning_rate": 0.05}),
        rule=Downpour(communication_window=2),
        num_workers=4,
    )
    state = eng.init_state(jax.random.PRNGKey(0), x[:8])
    blocks = list(epoch_window_iter(x, onehot, 4, 8, 2))  # 8 windows

    # warmup epoch compiles the window program (fast source: quiet)
    state, _ = eng.run_epoch_streaming(state, PrefetchRing(iter(blocks)))
    assert not eng.last_stream_report["link_bound"]

    ring = PrefetchRing(_SlowBlocks(blocks, 0.05), depth=2)
    with pytest.warns(RuntimeWarning, match="source is the bottleneck"):
        state, _ = eng.run_epoch_streaming(state, ring)
    assert eng.last_stream_report["link_bound"]
    assert ring.stall_seconds > 0
    snap = telemetry.metrics.snapshot()
    assert snap["datapipe_stall_seconds"]["value"] > 0
    assert "datapipe_prefetch_depth" in snap


def test_ring_gather_spans_on_producer_thread(live_telemetry):
    """Overlap is observable: gather spans carry the producer thread's tid,
    distinct from the consumer's — in a merged Chrome trace they overlap
    the main thread's step spans instead of serialising with them."""
    with telemetry.trace.span("consumer_step"):
        for _ in PrefetchRing(_toy_blocks(), depth=2):
            time.sleep(0.001)
    events = telemetry.trace.export()["traceEvents"]
    gathers = [e for e in events if e["name"] == "datapipe_gather"]
    steps = [e for e in events if e["name"] == "consumer_step"]
    assert gathers and steps
    assert {e["tid"] for e in gathers}.isdisjoint({e["tid"] for e in steps})


# ----------------------------------------------------------- resume cursor

def test_start_block_yields_identical_tail():
    plain = list(_toy_blocks(seed=1))
    tail = list(_toy_blocks(seed=1, start_block=3))
    assert len(tail) == len(plain) - 3
    for (a, b), (c, d) in zip(plain[3:], tail):
        assert a.tobytes() == c.tobytes()
        assert b.tobytes() == d.tobytes()


def test_start_block_bounds_validated():
    with pytest.raises(ValueError, match="start_block"):
        list(_toy_blocks(start_block=-1))
    with pytest.raises(ValueError, match="start_block"):
        list(_toy_blocks(start_block=99))
    # == n_windows is legal: an empty tail (resume landed on the boundary)
    assert list(_toy_blocks(seed=1, start_block=len(list(_toy_blocks(seed=1))))) == []


def test_data_state_json_and_rng_roundtrip():
    rng = np.random.default_rng(7)
    rng.permutation(10)  # advance past the seed state
    ds = DataState.capture(3, rng, block_cursor=5)
    ds2 = DataState.from_json(ds.to_json())
    assert (ds2.epoch, ds2.block_cursor) == (3, 5)
    restored = ds2.restore_rng(np.random.default_rng(0))
    np.testing.assert_array_equal(restored.permutation(16), rng.permutation(16))
    # shuffle-off runs carry no rng state; restore is a no-op
    ds3 = DataState.capture(1, None)
    assert ds3.rng_state is None
    fresh = np.random.default_rng(5)
    expected = np.random.default_rng(5).permutation(8)
    np.testing.assert_array_equal(ds3.restore_rng(fresh).permutation(8), expected)


# ------------------------------------------------------------ checkpointing

def _tiny_state():
    from distkeras_tpu.algorithms import Downpour
    from distkeras_tpu.models import MLP, FlaxModel
    from distkeras_tpu.parallel.engine import WindowedEngine

    eng = WindowedEngine(
        FlaxModel(MLP(features=(4,), num_classes=2)),
        loss="categorical_crossentropy",
        worker_optimizer=("sgd", {"learning_rate": 0.05}),
        rule=Downpour(communication_window=2), num_workers=2,
    )
    x = np.zeros((4, 8), np.float32)
    return eng.init_state(jax.random.PRNGKey(0), x)


def test_data_state_sidecar_save_restore(tmp_path):
    from distkeras_tpu import checkpoint as ckpt_mod

    d = str(tmp_path)
    state = _tiny_state()
    ckpt_mod.save_checkpoint(d, state, step=2)
    ckpt_mod.wait_until_finished()
    ds = DataState.capture(1, np.random.default_rng(3), block_cursor=2)
    ckpt_mod.save_data_state(d, ds, step=2)
    got = ckpt_mod.restore_data_state(d)  # step=None -> latest
    assert (got.epoch, got.block_cursor) == (1, 2)
    assert got.rng_state == ds.rng_state
    # a step without a sidecar restores None
    assert ckpt_mod.restore_data_state(d, step=99) is None


def test_manager_partial_then_boundary_save_and_gc(tmp_path):
    """save_partial writes model + sidecar; the SAME step's later boundary
    save must overwrite the partial (Orbax refuses overwrites unless the
    manager knows the step is partial) and remove the stale sidecar; _gc
    collects sidecars with their steps."""
    from distkeras_tpu.checkpoint import CheckpointManager, data_state_path

    d = str(tmp_path)
    mgr = CheckpointManager(d, every=1, keep=2)
    state = _tiny_state()
    ds = DataState.capture(1, np.random.default_rng(0), block_cursor=2)
    mgr.save_partial(state, epoch=1, data_state=ds)
    mgr.wait()
    assert os.path.exists(data_state_path(d, 2))
    assert mgr.restore_data_state(2).block_cursor == 2

    # epoch 1 completes: boundary save of the same step replaces the partial
    mgr.maybe_save(state, epoch=1)
    mgr.wait()
    assert mgr.latest() == 2
    assert not os.path.exists(data_state_path(d, 2))  # stale sidecar gone
    assert mgr.restore_data_state(2) is None

    # keep=2: step 2's sidecar-bearing successors gc together
    for epoch in (2, 3, 4):
        mgr.save_partial(state, epoch=epoch,
                         data_state=DataState(epoch=epoch, block_cursor=1))
    mgr.wait()
    assert not os.path.exists(data_state_path(d, 3))  # gc'd with step 3
    assert os.path.exists(data_state_path(d, 5))


def test_fresh_manager_detects_partial_step_from_sidecar(tmp_path):
    """The resume race: a killed run's step dir exists with a cursor>0
    sidecar; a FRESH manager (new process) must treat that step as partial
    and force-overwrite at the boundary save instead of crashing on
    Orbax's destination-exists error."""
    from distkeras_tpu.checkpoint import CheckpointManager, data_state_path

    d = str(tmp_path)
    state = _tiny_state()
    m1 = CheckpointManager(d, every=1)
    m1.save_partial(state, epoch=0,
                    data_state=DataState(epoch=0, block_cursor=1))
    m1.wait()

    m2 = CheckpointManager(d, every=1)  # the resuming process
    m2.maybe_save(state, epoch=0)       # same step 1, now a boundary save
    m2.wait()
    assert m2.latest() == 1
    assert not os.path.exists(data_state_path(d, 1))


# ------------------------------------------------------------------ sources

def test_host_shard_balanced_and_total():
    n = 103
    ranges = [host_shard(n, i, 4) for i in range(4)]
    sizes = [hi - lo for lo, hi in ranges]
    assert sum(sizes) == n and max(sizes) - min(sizes) <= 1
    assert ranges[0][0] == 0 and ranges[-1][1] == n
    for (_, hi), (lo, _) in zip(ranges, ranges[1:]):
        assert hi == lo
    with pytest.raises(ValueError):
        host_shard(n, 4, 4)


def test_array_source_shards_rows():
    feats = np.arange(20, dtype=np.float32).reshape(10, 2)
    labels = np.arange(10, dtype=np.int32)
    s0 = ArraySource(feats, labels, process_index=0, process_count=2)
    s1 = ArraySource(feats, labels, process_index=1, process_count=2)
    assert len(s0) == len(s1) == 10  # global
    assert s0.local_rows + s1.local_rows == 10
    f0, _ = s0.local_arrays()
    f1, _ = s1.local_arrays()
    np.testing.assert_array_equal(np.concatenate([f0, f1]), feats)
    # unsharded keeps everything
    assert ArraySource(feats, labels, shard=False).local_rows == 10
    with pytest.raises(ValueError, match="disagree"):
        ArraySource(feats, labels[:5])


def test_source_window_iter_matches_epoch_window_iter():
    feats = np.random.default_rng(0).normal(size=(48, 3)).astype(np.float32)
    labels = (np.arange(48) % 2).astype(np.int32)
    src = ArraySource(feats, labels, shard=False)
    a = list(src.window_iter(2, 4, 2, rng=np.random.default_rng(9)))
    b = list(epoch_window_iter(feats, labels, 2, 4, 2,
                               rng=np.random.default_rng(9)))
    for (ax, ay), (bx, by) in zip(a, b):
        assert ax.tobytes() == bx.tobytes() and ay.tobytes() == by.tobytes()


def test_array_source_from_dataframe(toy_classification):
    from distkeras_tpu.frame import from_numpy

    x, y, onehot = toy_classification
    src = ArraySource.from_dataframe(from_numpy(x, onehot), shard=False)
    f, l = src.local_arrays()
    assert f.dtype == np.float32 and f.shape == x.shape
    np.testing.assert_array_equal(f, x)


def test_memmap_source_single_file_and_shards(tmp_path):
    feats = np.arange(24, dtype=np.float32).reshape(12, 2)
    labels = np.arange(12, dtype=np.int32)
    fp, lp = str(tmp_path / "f.npy"), str(tmp_path / "l.npy")
    np.save(fp, feats)
    np.save(lp, labels)

    # single file: row-range shard, zero-copy view
    s0 = MemmapSource(fp, lp, process_index=0, process_count=2)
    s1 = MemmapSource(fp, lp, process_index=1, process_count=2)
    assert len(s0) == 12
    f0, _ = s0.local_arrays()
    f1, _ = s1.local_arrays()
    np.testing.assert_array_equal(np.concatenate([f0, f1]), feats)

    # file shards: round-robin assignment
    fa, la = str(tmp_path / "fa.npy"), str(tmp_path / "la.npy")
    fb, lb = str(tmp_path / "fb.npy"), str(tmp_path / "lb.npy")
    np.save(fa, feats[:5]); np.save(la, labels[:5])
    np.save(fb, feats[5:]); np.save(lb, labels[5:])
    m0 = MemmapSource([fa, fb], [la, lb], process_index=0, process_count=2)
    m1 = MemmapSource([fa, fb], [la, lb], process_index=1, process_count=2)
    assert len(m0) == 12 and m0.local_rows == 5 and m1.local_rows == 7
    with pytest.raises(ValueError, match="pair up"):
        MemmapSource([fa, fb], [la])
    with pytest.raises(ValueError, match="zero of"):
        MemmapSource([fa, fb], [la, lb], process_index=2, process_count=3)


# ------------------------------------------------------------------ packing

def test_pack_sequences_layout_and_efficiency():
    seqs = [np.arange(1, n + 1) for n in (5, 3, 7, 2, 4)]
    pb = pack_sequences(seqs, 8)
    assert pb.n_sequences == 5 and pb.total_tokens == 21
    assert pb.tokens.shape[1] == 8
    assert pb.efficiency == pytest.approx(21 / pb.tokens.size)
    assert pb.model_inputs().shape == pb.tokens.shape + (2,)
    # every sequence appears exactly once, contiguous, with per-segment
    # positions restarting at 0 and 1-based segment ids (0 = pad)
    found = 0
    for r in range(pb.tokens.shape[0]):
        segs = pb.segment_ids[r]
        assert segs[segs != 0].min(initial=99) >= 1
        for seg in range(1, segs.max() + 1):
            sel = segs == seg
            toks = pb.tokens[r][sel]
            match = [s for s in seqs if len(s) == len(toks)
                     and (s == toks).all()]
            assert match, (r, seg, toks)
            np.testing.assert_array_equal(pb.positions[r][sel],
                                          np.arange(sel.sum()))
            # derived labels: next token within the segment, -1 at its tail
            labs = pb.labels[r][sel]
            np.testing.assert_array_equal(labs[:-1], toks[1:])
            assert labs[-1] == -1
            found += 1
    assert found == 5
    # pads carry -1 labels
    assert (pb.labels[pb.segment_ids == 0] == -1).all()


def test_pack_sequences_deterministic():
    rng = np.random.default_rng(2)
    seqs = [rng.integers(1, 9, size=m) for m in rng.integers(1, 17, size=40)]
    a = pack_sequences(seqs, 16)
    b = pack_sequences([s.copy() for s in seqs], 16)
    np.testing.assert_array_equal(a.tokens, b.tokens)
    np.testing.assert_array_equal(a.segment_ids, b.segment_ids)


def test_pack_sequences_explicit_labels_and_errors():
    seqs = [np.array([1, 2, 3]), np.array([4, 5])]
    labels = [np.array([10, 20, 30]), np.array([40, 50])]
    pb = pack_sequences(seqs, 4, labels=labels)
    row0 = pb.labels[pb.segment_ids != 0]
    assert set(row0.tolist()) == {10, 20, 30, 40, 50}

    with pytest.raises(ValueError, match="width"):
        pack_sequences(seqs, 0)
    with pytest.raises(ValueError, match="no sequences"):
        pack_sequences([], 8)
    with pytest.raises(ValueError, match="empty sequence"):
        pack_sequences([np.array([1]), np.array([])], 8)
    with pytest.raises(ValueError, match="exceeds pack width"):
        pack_sequences([np.arange(9)], 8)
    with pytest.raises(ValueError, match="label"):
        pack_sequences(seqs, 8, labels=labels[:1])
    with pytest.raises(ValueError, match="tokens vs"):
        pack_sequences(seqs, 8, labels=[labels[0], labels[1][:1]])


def _packed_batch():
    seqs = [np.arange(1, n + 1) for n in (5, 3, 7, 2, 4)]
    return pack_sequences(seqs, 8)


def test_packed_transformer_lm_matches_unpacked():
    """The acceptance bar: packed segment-ID attention logits equal the
    per-sequence unpacked attention for every segment."""
    from distkeras_tpu.models.transformer import TransformerLM

    pb = _packed_batch()
    mi = jnp.asarray(pb.model_inputs())
    packed = TransformerLM(vocab_size=16, dim=32, heads=2, num_layers=2,
                           max_len=32, packed=True)
    plain = TransformerLM(vocab_size=16, dim=32, heads=2, num_layers=2,
                          max_len=32)
    # the packed model's param tree is the unpacked one's (the channel split
    # happens before any parameterised op) — parity via shared params
    params = packed.init(jax.random.PRNGKey(0), mi)["params"]
    packed_logits = np.asarray(packed.apply({"params": params}, mi))
    checked = 0
    for r in range(pb.tokens.shape[0]):
        for seg in range(1, int(pb.segment_ids[r].max()) + 1):
            sel = pb.segment_ids[r] == seg
            alone = plain.apply(
                {"params": params}, jnp.asarray(pb.tokens[r][sel][None]))
            np.testing.assert_allclose(
                np.asarray(alone[0]), packed_logits[r][sel], atol=2e-5)
            checked += 1
    assert checked == pb.n_sequences


def test_packed_staged_lm_matches_unpacked():
    from distkeras_tpu.models.staged import StagedLM

    pb = _packed_batch()
    mi = jnp.asarray(pb.model_inputs())
    packed = StagedLM(vocab_size=16, dim=32, heads=2, num_stages=2,
                      blocks_per_stage=1, max_len=32, packed=True)
    plain = StagedLM(vocab_size=16, dim=32, heads=2, num_stages=2,
                     blocks_per_stage=1, max_len=32)
    params, mstate = packed.init(jax.random.PRNGKey(1), mi)
    packed_logits, _ = packed.apply(params, mstate, mi)
    packed_logits = np.asarray(packed_logits)
    for r in range(pb.tokens.shape[0]):
        for seg in range(1, int(pb.segment_ids[r].max()) + 1):
            sel = pb.segment_ids[r] == seg
            alone, _ = plain.apply(params, mstate,
                                   jnp.asarray(pb.tokens[r][sel][None]))
            np.testing.assert_allclose(
                np.asarray(alone[0]), packed_logits[r][sel], atol=2e-5)


def test_masked_token_crossentropy_ignores_negative_labels():
    from distkeras_tpu.ops.losses import get_loss

    loss = get_loss("masked_token_crossentropy")
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(size=(2, 6, 8)).astype(np.float32))
    labels = np.array([[1, 2, 3, -1, -1, -1], [4, 5, -1, -1, -1, -1]])
    got = float(loss(logits, jnp.asarray(labels)))
    # reference: plain token CE over only the real positions
    import optax

    per = optax.softmax_cross_entropy_with_integer_labels(
        logits, jnp.maximum(jnp.asarray(labels), 0))
    mask = labels >= 0
    want = float((np.asarray(per) * mask).sum() / mask.sum())
    assert got == pytest.approx(want, rel=1e-6)
    # all-masked batch: finite zero, not NaN
    assert float(loss(logits, jnp.full_like(jnp.asarray(labels), -1))) == 0.0
    assert get_loss("packed_crossentropy") is not None  # alias resolves


# --------------------------------------------------- trainer-level parity

def _lm_df(n=256, d=8):
    from distkeras_tpu.frame import DataFrame

    g = np.random.default_rng(0)
    x = g.normal(size=(n, d)).astype(np.float32)
    y = (x.sum(1) > 0).astype(np.int32)
    onehot = np.eye(2, dtype=np.float32)[y]
    return DataFrame({"features": list(x), "label": list(onehot)})


def _mlp():
    from distkeras_tpu.models import MLP, FlaxModel

    return FlaxModel(MLP(features=(16,), num_classes=2))


def _downpour(**kw):
    import distkeras_tpu as dk

    base = dict(num_workers=8, batch_size=4, num_epoch=2,
                communication_window=4, streaming=True, seed=3)
    base.update(kw)
    return dk.DOWNPOUR(_mlp(), "categorical_crossentropy", "sgd", **base)


def _assert_trees_bitwise(a, b, msg=""):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        assert np.asarray(x).tobytes() == np.asarray(y).tobytes(), msg


@pytest.mark.parametrize("compute_dtype", [None, "bfloat16"])
def test_trainer_prefetch_trajectory_bitwise(compute_dtype):
    """prefetch>0 (ring + producer-thread device put) reproduces the
    unprefetched streaming trajectory bit-for-bit — float32 and the fused
    bf16 host gather+cast."""
    df = _lm_df()
    kw = {} if compute_dtype is None else {"compute_dtype": compute_dtype}
    p0 = _downpour(prefetch=0, **kw).train(df, shuffle=True).params
    p2 = _downpour(prefetch=2, **kw).train(df, shuffle=True).params
    _assert_trees_bitwise(p0, p2, f"prefetch diverged ({compute_dtype})")


def test_mid_epoch_kill_resume_bitwise(tmp_path, monkeypatch):
    """The resume acceptance bar: kill a run mid-epoch (after a block
    checkpoint), restore model + DataState in a fresh trainer, consume
    exactly the remaining blocks, and land on the uninterrupted run's final
    params bit-for-bit."""
    import distkeras_tpu.data as data_mod
    from distkeras_tpu.checkpoint import latest_step, restore_data_state

    df = _lm_df()

    def mk(ckdir, **kw):
        return _downpour(num_epoch=3, communication_window=2, prefetch=2,
                         checkpoint_dir=ckdir, checkpoint_blocks=2, **kw)

    dir_a, dir_b = str(tmp_path / "a"), str(tmp_path / "b")
    p_uninterrupted = mk(dir_a).train(df, shuffle=True).params

    # 4 blocks/epoch; kill the SECOND epoch's iterator at block 3 — after
    # the cursor-2 partial save, before the epoch completes
    orig_iter = data_mod.epoch_window_iter
    calls = {"n": 0}

    def killing_iter(*a, **kw):
        calls["n"] += 1
        inner = orig_iter(*a, **kw)
        if calls["n"] == 2:
            def gen():
                for i, blk in enumerate(inner):
                    if i == 3:
                        raise RuntimeError("simulated preemption")
                    yield blk
            return gen()
        return inner

    monkeypatch.setattr(data_mod, "epoch_window_iter", killing_iter)
    with pytest.raises(RuntimeError, match="preemption"):
        mk(dir_b).train(df, shuffle=True)
    monkeypatch.setattr(data_mod, "epoch_window_iter", orig_iter)

    ds = restore_data_state(dir_b)
    assert ds is not None
    assert (ds.epoch, ds.block_cursor) == (1, 2)
    assert ds.rng_state is not None
    assert latest_step(dir_b) == 2  # partial step_2 (epoch 1 in flight)

    p_resumed = mk(dir_b, resume=True).train(df, shuffle=True).params
    _assert_trees_bitwise(p_uninterrupted, p_resumed,
                          "resumed trajectory diverged")


def test_checkpoint_blocks_requires_streaming():
    import distkeras_tpu as dk

    with pytest.raises(ValueError, match="streaming"):
        dk.DOWNPOUR(_mlp(), "categorical_crossentropy", "sgd",
                    num_workers=2, checkpoint_blocks=2)
    with pytest.raises(ValueError, match="prefetch"):
        dk.DOWNPOUR(_mlp(), "categorical_crossentropy", "sgd",
                    num_workers=2, streaming=True, prefetch=-1)
