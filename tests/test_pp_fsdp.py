"""Pipeline x fsdp: ZeRO-style stage sharding of the non-staged leaves.

The staged layout's documented trade (models/staged.py) was that the embed
table and head are replicated on every stage device — at LM scale those
dominate a stage's blocks.  ``PipelineEngine(fsdp=True)`` stores each
evenly-splitting embed/head leaf (and its optimizer moments / rule state,
which mirror param shapes) 1/num_stages per stage and all-gathers at use
inside the pipelined view; ``all_gather``'s transpose (``psum_scatter``)
hands each stage its own gradient shard, and the commit rules run
elementwise on shards.  Sharding is layout, not math — the trajectory must
equal the replicated-embed pipeline run exactly.
"""

import jax
import numpy as np
import pytest

from distkeras_tpu.algorithms import Downpour
from distkeras_tpu.models import StagedLM, StagedTransformer
from distkeras_tpu.parallel import PipelineEngine

from conftest import epoch_data, toy_text


def _staged(num_stages=2, per_stage=1):
    return StagedTransformer(
        vocab_size=50, num_classes=2, dim=32, heads=2,
        num_stages=num_stages, blocks_per_stage=per_stage, max_len=64,
    )


def _engine(adapter, fsdp, *, optimizer=("sgd", {"learning_rate": 0.05}),
            loss="categorical_crossentropy", devices=None):
    return PipelineEngine(
        adapter, loss, optimizer, Downpour(2),
        num_workers=2, microbatches=2, metrics=(), fsdp=fsdp,
        devices=devices if devices is not None else jax.devices()[:4],
    )


def _run(engine, xs, ys, epochs=2):
    xs_d, ys_d = engine.shard_batches(xs, ys)
    state = engine.init_state(jax.random.PRNGKey(0), xs[0, 0, 0])
    losses = []
    for _ in range(epochs):
        state, stats = engine.run_epoch(state, xs_d, ys_d)
        losses.append(np.asarray(stats["loss"]))
    return engine.gather_center(state), np.concatenate(losses), state


def test_pp_fsdp_trajectory_equals_replicated():
    """2 workers x 2 stages, sharded vs replicated embed/head: identical
    losses and center (the gather/scatter round-trip adds no math)."""
    x, _, onehot = toy_text()
    xs, ys = epoch_data(x, onehot, num_workers=2, n_windows=2, window=2, batch=8)

    center_f, loss_f, _ = _run(_engine(_staged(), True), xs, ys)
    center_r, loss_r, _ = _run(_engine(_staged(), False), xs, ys)

    np.testing.assert_allclose(loss_f, loss_r, rtol=2e-5, atol=1e-6)
    for a, b in zip(jax.tree.leaves(center_f), jax.tree.leaves(center_r)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=1e-6)


def test_pp_fsdp_state_is_stage_sharded():
    """The vocab embedding — the leaf the flag exists for — stores
    1/num_stages per device, in center, local and optimizer trees; the
    layout survives an epoch (the scan carry is not re-replicated)."""
    x, _, onehot = toy_text(n=64)
    xs, ys = epoch_data(x, onehot, num_workers=2, n_windows=1, window=2, batch=8)
    eng = _engine(_staged(), True,
                  optimizer=("adam", {"learning_rate": 1e-3}))
    xs_d, ys_d = eng.shard_batches(xs, ys)
    state = eng.init_state(jax.random.PRNGKey(0), xs[0, 0, 0])
    state, _ = eng.run_epoch(state, xs_d, ys_d)

    tok = state.center_params["embed"]["tok_embed"]["embedding"]
    assert tok.shape == (50, 32)
    shard = tok.addressable_shards[0].data.shape
    assert shard == (25, 32), shard

    ltok = state.local_params["embed"]["tok_embed"]["embedding"]
    assert ltok.shape == (2, 50, 32)
    lshard = ltok.addressable_shards[0].data.shape
    assert lshard == (1, 25, 32), lshard

    # adam moments mirror the param shapes and must ride the same layout
    # (ZeRO's actual point: no device holds another stage's moments)
    moments = [l for l in jax.tree.leaves(state.opt_state)
               if l.shape == (2, 50, 32)]
    assert moments, "expected param-shaped adam moment leaves"
    for m in moments:
        assert m.addressable_shards[0].data.shape == (1, 25, 32)

    # non-divisible leaves (the 2-wide head bias) stay replicated
    bias = state.center_params["head"]["out"]["bias"]
    assert bias.addressable_shards[0].data.shape == bias.shape


def test_pp_fsdp_staged_lm_trains():
    """fsdp on the staged causal LM — vocab-sharded embedding AND head
    under per-token labels — still converges."""
    rng = np.random.default_rng(0)
    x = rng.integers(0, 32, size=(128, 16)).astype(np.int32)
    xs, ys = epoch_data(x, x, num_workers=2, n_windows=2, window=2, batch=8)
    ys = ys.astype(np.int32)
    adapter = StagedLM(vocab_size=32, dim=32, heads=2, num_stages=2,
                       blocks_per_stage=1, max_len=16)
    eng = _engine(adapter, True, loss="token_crossentropy",
                  optimizer=("adam", {"learning_rate": 2e-3}))
    xs_d, ys_d = eng.shard_batches(xs, ys)
    state = eng.init_state(jax.random.PRNGKey(0), xs[0, 0, 0])
    losses = []
    for _ in range(6):
        state, stats = eng.run_epoch(state, xs_d, ys_d)
        losses.append(float(np.asarray(stats["loss"]).mean()))
    assert losses[-1] < losses[0] * 0.9, losses


def test_pp_fsdp_state_from_center_resumes():
    """Elastic resume rebuilds a SHARDED pipeline state from host center
    trees (this also covers the pipeline engine's state_from_center path,
    which previously had no coverage at all)."""
    x, _, onehot = toy_text(n=64)
    xs, ys = epoch_data(x, onehot, num_workers=2, n_windows=1, window=2, batch=8)
    eng = _engine(_staged(), True)
    xs_d, ys_d = eng.shard_batches(xs, ys)
    state = eng.init_state(jax.random.PRNGKey(0), xs[0, 0, 0])
    state, _ = eng.run_epoch(state, xs_d, ys_d)
    center_host = jax.tree.map(np.asarray, eng.gather_center(state))
    rule_host = jax.tree.map(np.asarray, state.center_rule)

    fresh = _engine(_staged(), True)
    resumed = fresh.state_from_center(
        jax.random.PRNGKey(1), center_host, rule_host, {}, 1,
    )
    tok = resumed.center_params["embed"]["tok_embed"]["embedding"]
    assert tok.addressable_shards[0].data.shape == (25, 32)
    np.testing.assert_array_equal(
        np.asarray(tok),
        center_host["embed"]["tok_embed"]["embedding"],
    )
    # and the resumed state trains
    resumed, stats = fresh.run_epoch(resumed, xs_d, ys_d)
    assert np.isfinite(np.asarray(stats["loss"])).all()


def test_pp_fsdp_through_trainer_api():
    """DOWNPOUR(..., pipeline_stages=2, fsdp=True) end to end."""
    import distkeras_tpu as dk

    x, y, onehot = toy_text(n=256)
    df = dk.from_numpy(x, onehot)
    t = dk.DOWNPOUR(_staged(), loss="categorical_crossentropy",
                    worker_optimizer=("adam", {"learning_rate": 2e-3}),
                    num_workers=4, batch_size=16, num_epoch=10,
                    communication_window=2, pipeline_stages=2, fsdp=True)
    trained = t.train(df)
    h = t.get_history()["loss"]
    assert h[-1] < h[0] * 0.8, h
    preds = trained.predict(x)
    assert np.mean(np.argmax(preds, -1) == y) > 0.75


def test_pp_tp_fsdp_trajectory_matches_pp_fsdp():
    """Three axes + stage-sharded embed/head: 2 workers x 2 stages x 2
    model with fsdp equals the 2-axis fsdp run (the auto model axis and
    the stage sharding are both layout, not math) — backs the README's
    'composes with tp_shards' claim with an assertion."""
    x, _, onehot = toy_text()
    xs, ys = epoch_data(x, onehot, num_workers=2, n_windows=2, window=2, batch=8)

    tp = PipelineEngine(_staged(), "categorical_crossentropy",
                        ("sgd", {"learning_rate": 0.05}), Downpour(2),
                        num_workers=2, microbatches=2, metrics=(),
                        tp_shards=2, fsdp=True)
    center_tp, loss_tp, _ = _run(tp, xs, ys)
    center_f, loss_f, _ = _run(_engine(_staged(), True), xs, ys)

    np.testing.assert_allclose(loss_tp, loss_f, rtol=2e-4, atol=2e-5)
    for a, b in zip(jax.tree.leaves(center_tp), jax.tree.leaves(center_f)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-4)


def test_pp_fsdp_rejects_unauditable_optimizers():
    """Custom optax transforms may reduce across parameters (global-norm
    clipping) — stage-inconsistent on sharded leaves; fsdp=True accepts
    only named (elementwise) optimizers."""
    import optax

    with pytest.raises(ValueError, match="named worker"):
        PipelineEngine(_staged(), "categorical_crossentropy",
                       optax.sgd(0.05), Downpour(2), fsdp=True,
                       devices=jax.devices()[:4], num_workers=2)


def test_pp_fsdp_single_stage_rejected():
    with pytest.raises(ValueError, match="num_stages"):
        PipelineEngine(_staged(num_stages=1), "categorical_crossentropy",
                       "sgd", Downpour(2), fsdp=True,
                       devices=jax.devices()[:2])
