"""Test configuration: fake an 8-device CPU mesh before any backend init.

This is the rebuild's analogue of the reference's Spark ``local[N]`` mode
(SURVEY.md §4): the full distributed protocol runs on one machine by making
XLA expose N host devices, so every collective path (commit psums, center
replication, staleness clocks) is exercised without TPU hardware.

Env vars alone are not enough here: the sandbox pre-imports jax with
JAX_PLATFORMS pointing at the TPU tunnel, so we must override through
``jax.config`` before the first backend query.
"""

import os

os.environ.setdefault("KERAS_BACKEND", "jax")

import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # Older jax (< 0.5): the config option doesn't exist; the XLA flag does
    # the same thing as long as it lands before the first backend query.
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    )

import numpy as np
import pytest


@pytest.fixture(autouse=True)
def _telemetry_files_to_tmp(tmp_path, monkeypatch):
    """The CI matrix runs the whole suite with DISTKERAS_TELEMETRY=1; keep
    each test's flush() output (trace_*.json / metrics_*.jsonl) out of the
    repo checkout unless a test points the dir somewhere itself."""
    if os.environ.get("DISTKERAS_TELEMETRY") and not os.environ.get(
            "DISTKERAS_TELEMETRY_DIR"):
        monkeypatch.setenv("DISTKERAS_TELEMETRY_DIR", str(tmp_path))
    yield


@pytest.fixture(scope="session")
def toy_classification():
    """Small linearly-separable 2-class problem: fast convergence checks."""
    rng = np.random.default_rng(0)
    n = 512
    x = rng.normal(size=(n, 8)).astype(np.float32)
    w = rng.normal(size=(8,))
    y = (x @ w > 0).astype(np.int32)
    onehot = np.zeros((n, 2), np.float32)
    onehot[np.arange(n), y] = 1.0
    return x, y, onehot


def toy_text(n=128, seq=16, vocab=50, seed=0):
    """Token-classification toy task shared by the parallelism test files:
    class = whether token 7 appears more often than token 3 (needs the
    whole sequence, so attention/pipelines must actually work)."""
    rng = np.random.default_rng(seed)
    x = rng.integers(0, vocab, size=(n, seq)).astype(np.int32)
    y = ((x == 7).sum(1) > (x == 3).sum(1)).astype(np.int32)
    return x, y, np.eye(2, dtype=np.float32)[y]


def epoch_data(x, onehot, num_workers, n_windows, window, batch):
    """Tile (x, onehot) into the engines' epoch layout
    [workers, windows, window, batch, ...]."""
    n_need = num_workers * n_windows * window * batch
    reps = -(-n_need // len(x))
    xs = np.tile(x, (reps, 1))[:n_need].reshape(
        num_workers, n_windows, window, batch, -1)
    ys = np.tile(onehot, (reps, 1))[:n_need].reshape(
        num_workers, n_windows, window, batch, -1)
    return xs, ys
