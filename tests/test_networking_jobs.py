"""Networking codec + job-deployment daemon tests."""

import socket
import threading

import numpy as np
import pytest

from distkeras_tpu.job_deployment import Job, PunchcardServer
from distkeras_tpu.networking import (
    _decode,
    _encode,
    determine_host_address,
    recv_data,
    send_data,
)


def test_codec_roundtrip_scalars_and_arrays():
    msg = {
        "action": "commit",
        "window": 5,
        "weights": [np.arange(6, dtype=np.float32).reshape(2, 3), np.ones(4)],
        "nested": {"flag": True, "none": None, "blob": b"\x00\x01"},
    }
    out = _decode(_encode(msg))
    assert out["action"] == "commit" and out["window"] == 5
    np.testing.assert_array_equal(out["weights"][0], msg["weights"][0])
    np.testing.assert_array_equal(out["weights"][1], msg["weights"][1])
    assert out["nested"]["flag"] is True and out["nested"]["none"] is None
    assert out["nested"]["blob"] == b"\x00\x01"


def test_send_recv_over_socket():
    server = socket.socket()
    server.bind(("127.0.0.1", 0))
    server.listen(1)
    port = server.getsockname()[1]
    received = {}

    def serve():
        conn, _ = server.accept()
        received["msg"] = recv_data(conn)
        send_data(conn, {"ok": 1})
        conn.close()

    t = threading.Thread(target=serve)
    t.start()
    client = socket.create_connection(("127.0.0.1", port))
    send_data(client, {"hello": np.zeros(3)})
    reply = recv_data(client)
    t.join(timeout=5)
    server.close()
    client.close()
    assert reply == {"ok": 1}
    np.testing.assert_array_equal(received["msg"]["hello"], np.zeros(3))


def test_determine_host_address_returns_ip():
    addr = determine_host_address()
    assert isinstance(addr, str) and addr.count(".") == 3


@pytest.fixture()
def punchcard():
    server = PunchcardServer(port=0, secret="s3cret")
    server.start()
    yield server
    server.stop()


def test_job_submit_run_finish(punchcard):
    job = Job("127.0.0.1", punchcard.port, secret="s3cret",
              script="print('result:', 6 * 7)")
    job.submit()
    st = job.wait(timeout=30)
    assert st["status"] == "finished"
    assert "result: 42" in st["output"]


def test_job_failure_reported(punchcard):
    job = Job("127.0.0.1", punchcard.port, secret="s3cret",
              script="raise SystemExit(3)")
    job.submit()
    st = job.wait(timeout=30)
    assert st["status"] == "failed" and st["returncode"] == 3


def test_status_verb_reports_telemetry_surface(punchcard, tmp_path,
                                               monkeypatch):
    """The status verb carries each job's telemetry dir, live HTTP address
    (None while flightdeck is off), and a last-heartbeat timestamp derived
    from the job's telemetry files."""
    import os

    from distkeras_tpu import telemetry

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    monkeypatch.setenv("PYTHONPATH", repo)
    monkeypatch.setenv("DISTKERAS_TELEMETRY_DIR", str(tmp_path))
    telemetry.configure(True)
    try:
        job = Job("127.0.0.1", punchcard.port, secret="s3cret",
                  script="from distkeras_tpu import telemetry\n"
                         "telemetry.metrics.counter('c').inc()\n"
                         "telemetry.flush()\n")
        job.submit()
        st = job.wait(timeout=120)
        assert st["status"] == "finished", st.get("output")
        assert st["telemetry_dir"] == os.path.join(
            punchcard.workdir, "telemetry", job.job_id)
        assert st["http"] is None  # no DISTKERAS_TELEMETRY_HTTP: no exporter
        # heartbeat falls back to the flushed files' mtime when there is no
        # live exporter to ask
        assert isinstance(st["last_heartbeat"], float)
    finally:
        telemetry.trace.reset()
        telemetry.metrics.reset()
        telemetry.configure(None)


def test_status_verb_without_telemetry_has_null_surface(punchcard):
    job = Job("127.0.0.1", punchcard.port, secret="s3cret",
              script="print('ok')")
    job.submit()
    st = job.wait(timeout=30)
    assert st["status"] == "finished"
    assert st["telemetry_dir"] is None
    assert st["http"] is None
    assert st["last_heartbeat"] is None


def test_job_bad_secret_denied(punchcard):
    job = Job("127.0.0.1", punchcard.port, secret="wrong", script="print(1)")
    with pytest.raises(RuntimeError):
        job.submit()


def test_kafka_producer_tcp_stream():
    """The standalone producer script (examples/kafka_producer.py) streams
    batches to a consumer in another process over the package wire codec —
    the reference Kafka-pipeline split, demonstrable without Kafka."""
    import os
    import socket as socket_mod
    import subprocess
    import sys
    import time

    import numpy as np

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    s = socket_mod.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    proc = subprocess.Popen(
        [sys.executable, os.path.join(repo, "examples", "kafka_producer.py"),
         "--port", str(port), "--batches", "5", "--rows", "64", "--features", "8"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env={**os.environ, "PYTHONPATH": repo},
    )
    try:
        sys.path.insert(0, os.path.join(repo, "examples"))
        from streaming_inference import tcp_batches

        deadline = time.monotonic() + 60
        batches = None
        while time.monotonic() < deadline:
            try:
                # Retry only the pre-connect phase: the producer accepts a
                # single consumer, so a post-connect transport error must
                # propagate rather than be retried into ConnectionRefused.
                batches = list(tcp_batches(f"tcp://127.0.0.1:{port}"))
                break
            except ConnectionRefusedError:
                time.sleep(0.5)
        assert batches is not None, "could not connect to producer"
        assert len(batches) == 5
        assert all(isinstance(b, np.ndarray) and b.shape == (64, 8) for b in batches)
        out, _ = proc.communicate(timeout=30)
        assert proc.returncode == 0, out
        assert "done, 320 rows" in out
    finally:
        if proc.poll() is None:
            proc.kill()
