"""Serving-engine tests: continuous batching must emit tokens bitwise
identical to ``greedy_generate`` under staggered concurrent arrival with ONE
compiled decode step (retrace pin via ``install_jax_hooks``); slots and KV
pages retire and get reused; seeded sampling is deterministic and independent
of co-batched traffic; the bounded queue sheds load at admission; and the SLO
metrics schema is pinned three ways — golden Prometheus text, a live
flightdeck ``/metrics`` scrape, and the ``/generate`` HTTP endpoint."""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest

from distkeras_tpu import telemetry
from distkeras_tpu.models import StagedLM, TransformerLM
from distkeras_tpu.models.generate import (
    greedy_generate_module,
    greedy_generate_staged,
)
from distkeras_tpu.serving import (
    GenerateRequest,
    PagedKVCache,
    QueueFull,
    ServingEngine,
    install_http_endpoint,
    serving_metrics,
)
from distkeras_tpu.telemetry.flightdeck import correlate
from distkeras_tpu.telemetry.flightdeck import server as server_mod
from distkeras_tpu.telemetry.metrics import Registry, install_jax_hooks

VOCAB = 23
GOLDEN = os.path.join(os.path.dirname(os.path.abspath(__file__)), "golden")


@pytest.fixture(autouse=True)
def clean_serving(tmp_path, monkeypatch):
    monkeypatch.setenv("DISTKERAS_TELEMETRY_DIR", str(tmp_path))
    telemetry.configure(True)
    telemetry.metrics.reset()
    correlate.set_run_id("servetest")
    yield
    server_mod.stop()
    server_mod.configure(None)
    telemetry.metrics.reset()
    correlate.set_run_id(None)
    telemetry.configure(None)


@pytest.fixture(scope="module")
def lm():
    """One tiny TransformerLM + params shared by the whole module (engines
    recompile per instance; the params don't need to)."""
    module = TransformerLM(vocab_size=VOCAB, dim=16, heads=2, num_layers=2,
                           max_len=32)
    params = module.init(jax.random.PRNGKey(0),
                         np.zeros((1, 4), np.int32))["params"]
    return module, params


@pytest.fixture(scope="module")
def shared_engine(lm):
    """One engine (private registry) shared by every test that doesn't need
    a special configuration: the prefill/decode programs compile once for
    the whole module, and reuse across tests doubles as an endurance check —
    slots, pages, and per-request RNG chains must come back clean between
    tests."""
    module, params = lm
    engine = ServingEngine(module, params, num_slots=3, page_size=8,
                           registry=Registry())
    yield engine
    engine.stop()


@pytest.fixture
def make_engine():
    """Engine factory that guarantees ``stop()`` at teardown.  Default
    registry is a private one so tests don't cross-pollute the global
    scrape; pass ``registry=None`` explicitly to use the global."""
    engines = []

    def factory(model, params, **kw):
        kw.setdefault("registry", Registry())
        engine = ServingEngine(model, params, **kw)
        engines.append(engine)
        return engine

    yield factory
    for engine in engines:
        engine.stop()


def _ref(module, params, prompt, steps):
    """Per-request reference continuation from the lockstep greedy decoder."""
    out = greedy_generate_module(
        module, params, np.asarray([prompt], np.int32), steps
    )
    return out[0, len(prompt):].tolist()


def _get(addr, path, timeout=30):
    with urllib.request.urlopen(f"http://{addr}{path}", timeout=timeout) as r:
        return r.status, r.read().decode("utf-8")


def _post(addr, path, payload, timeout=30):
    req = urllib.request.Request(
        f"http://{addr}{path}", data=json.dumps(payload).encode("utf-8"),
        headers={"Content-Type": "application/json"}, method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, r.read().decode("utf-8")


# ------------------------------------------------------------- paged cache


def test_paged_cache_alloc_free_cycle():
    cache = PagedKVCache(num_layers=1, num_slots=2, page_size=4,
                         pages_per_slot=3, heads=2, head_dim=4)
    total = cache.pages_free
    assert total == 2 * 3  # default pool: full context per slot, + scratch
    assert cache.pages_needed(5) == 2
    assert cache.max_context() == 12

    cache.alloc(0, 2)
    assert cache.pages_in_use == 2
    assert (cache.tables[0, :2] > 0).all()  # real pages, never scratch
    assert cache.tables[0, 2] == 0          # unallocated entry -> scratch
    cache.alloc(1, 3)
    assert not cache.can_alloc(total)
    with pytest.raises(ValueError, match="table size"):
        cache.alloc(0, 2)  # would overflow slot 0's table

    assert cache.free(0) == 2
    assert (cache.tables[0] == 0).all()
    cache.free(1)
    assert cache.pages_in_use == 0 and cache.pages_free == total


# ----------------------------------------------------- greedy token identity


def test_staggered_concurrent_greedy_matches_greedy_generate(lm,
                                                             shared_engine):
    """Acceptance: >=3 requests admitted while others are mid-decode emit
    exactly the tokens the per-request lockstep decoder emits."""
    module, params = lm
    engine = shared_engine
    rng = np.random.default_rng(1)
    lengths = (3, 7, 5)
    steps = (8, 6, 10)
    prompts = [rng.integers(0, VOCAB, size=n).tolist() for n in lengths]
    refs = [_ref(module, params, p, s) for p, s in zip(prompts, steps)]

    pendings = []
    for prompt, s in zip(prompts, steps):
        pendings.append(
            engine.submit(GenerateRequest(prompt=prompt, max_new_tokens=s))
        )
        time.sleep(0.02)  # stagger: later requests join a running batch
    results = [p.result(timeout=120) for p in pendings]

    for result, ref, prompt in zip(results, refs, prompts):
        assert result is not None and result.finish_reason == "length"
        assert result.tokens == ref
        assert result.prompt == prompt
        assert result.ttft_s > 0 and result.latency_s >= result.ttft_s


def test_staged_lm_tokens_match(make_engine):
    module = StagedLM(vocab_size=VOCAB, dim=16, heads=2, num_stages=2,
                      blocks_per_stage=1, max_len=32)
    params, _ = module.init(jax.random.PRNGKey(1), np.zeros((1, 4), np.int32))
    prompt = [3, 1, 4, 1, 5]
    ref = greedy_generate_staged(
        module, params, np.asarray([prompt], np.int32), 6
    )[0, len(prompt):].tolist()
    engine = make_engine(module, params, num_slots=2, page_size=8)
    result = engine.generate(prompt, max_new_tokens=6, timeout=120)
    assert result.tokens == ref


def test_slot_retirement_and_reuse(lm, shared_engine):
    """More requests than slots: every slot must retire and be re-admitted
    into, and every KV page must come back to the pool."""
    module, params = lm
    engine = shared_engine
    rng = np.random.default_rng(2)
    # twice as many requests as slots; lengths cycle through two values so
    # the lockstep reference decoder compiles only two programs
    prompts = [rng.integers(0, VOCAB, size=n).tolist()
               for n in (3, 5, 3, 5, 3, 5)]
    refs = [_ref(module, params, p, 5) for p in prompts]
    pendings = [engine.submit(GenerateRequest(prompt=p, max_new_tokens=5))
                for p in prompts]
    results = [p.result(timeout=120) for p in pendings]
    assert [r.tokens for r in results] == refs

    deadline = time.monotonic() + 10
    while engine.stats()["active_slots"] and time.monotonic() < deadline:
        time.sleep(0.01)
    stats = engine.stats()
    assert stats["active_slots"] == 0 and stats["pages_in_use"] == 0


def test_eos_retires_early(lm, shared_engine):
    module, params = lm
    engine = shared_engine
    prompt = [2, 7, 1, 8, 4]  # length 5: reference program already compiled
    ref = _ref(module, params, prompt, 10)
    eos = ref[3]
    k = ref.index(eos)  # first emission of the eos token
    result = engine.generate(prompt, max_new_tokens=10, eos_id=eos,
                             timeout=120)
    assert result.finish_reason == "eos"
    assert result.tokens == ref[:k + 1]


# ------------------------------------------------------------------ sampling


def test_seeded_sampling_deterministic_and_traffic_independent(
        lm, shared_engine):
    module, params = lm
    engine = shared_engine
    prompt = [5, 9, 2]
    knobs = dict(max_new_tokens=8, temperature=0.9, top_k=7, top_p=0.95,
                 seed=123, timeout=120)
    alone = engine.generate(prompt, **knobs)
    assert engine.generate(prompt, **knobs).tokens == alone.tokens

    other_seed = engine.generate(prompt, **{**knobs, "seed": 7})
    assert other_seed.tokens != alone.tokens

    # same request co-batched with greedy traffic: tokens must not change
    # (each request's RNG chain splits only on its own tokens)
    rng = np.random.default_rng(3)
    noise = [engine.submit(GenerateRequest(
        prompt=rng.integers(0, VOCAB, size=6).tolist(), max_new_tokens=10))
        for _ in range(2)]
    busy = engine.generate(prompt, **knobs)
    assert busy.tokens == alone.tokens
    assert all(p.result(timeout=120) is not None for p in noise)


# -------------------------------------------------------------- backpressure


def test_queue_backpressure_rejects_and_counts(lm, make_engine):
    module, params = lm
    registry = Registry()
    engine = make_engine(module, params, queue_size=2, registry=registry)
    engine.start = lambda: None  # hold the loop: the queue cannot drain
    held = [engine.submit(GenerateRequest(prompt=[1, 2], max_new_tokens=2))
            for _ in range(2)]
    with pytest.raises(QueueFull):
        engine.submit(GenerateRequest(prompt=[1, 2], max_new_tokens=2))
    snap = registry.snapshot()
    assert snap["serving_requests_rejected_total"]["value"] == 1.0
    assert snap["serving_queue_depth"]["value"] == 2.0

    del engine.start  # restore the class method; held requests drain
    engine.start()
    results = [p.result(timeout=120) for p in held]
    assert all(r is not None and r.finish_reason == "length" for r in results)


def test_unservable_requests_rejected_loudly(lm, shared_engine):
    module, params = lm
    engine = shared_engine  # width == max_len == 32
    with pytest.raises(ValueError, match="prompt length"):
        engine.submit(GenerateRequest(prompt=list(range(32))))
    with pytest.raises(ValueError, match="vocabulary"):
        engine.submit(GenerateRequest(prompt=[VOCAB + 5]))
    with pytest.raises(ValueError, match="non-empty"):
        engine.submit(GenerateRequest(prompt=[]))


# ------------------------------------------------------------- retrace pin


def test_one_compiled_decode_step_across_staggered_traffic(lm,
                                                           shared_engine):
    """Acceptance: after one warmup request per prefill bucket, arbitrary
    mixes of prompt lengths, sampling knobs, and EOS must add ZERO jax
    compile/trace events — admitting a request is data movement and a
    bucket hit, never a retrace (DK102)."""
    module, params = lm
    install_jax_hooks()
    # a throwaway compile proves the hook is live (the counter only exists
    # once an event fires — the shared engine may already be warm)
    probe = jax.jit(lambda x: x + 1)
    probe(np.ones(3))
    engine = shared_engine
    # warm every bucket the traffic below can hit (page_size=8 ladder:
    # lengths <=8 -> bucket 8, lengths 9..16 -> bucket 16)
    engine.generate([1, 2, 3], max_new_tokens=3, timeout=120)
    engine.generate(list(range(1, 11)), max_new_tokens=3, timeout=120)

    base = telemetry.metrics.snapshot()["jax_compiles_total"]["value"]
    assert base >= 1
    rng = np.random.default_rng(4)
    pendings = []
    for i, n in enumerate((2, 8, 5, 11, 3)):
        pendings.append(engine.submit(GenerateRequest(
            prompt=rng.integers(0, VOCAB, size=n).tolist(),
            max_new_tokens=4 + i,
            temperature=0.0 if i % 2 else 0.8,
            top_k=5 if i == 2 else 0,
            top_p=0.9 if i == 3 else 1.0,
            seed=i,
            eos_id=(1 if i == 4 else None),
        )))
        time.sleep(0.01)
    assert all(p.result(timeout=120) is not None for p in pendings)
    after = telemetry.metrics.snapshot()["jax_compiles_total"]["value"]
    assert after == base, f"{after - base} recompiles after warmup"


# ------------------------------------------------------------------ metrics


def test_serving_metrics_schema_golden():
    """The SLO instrument schema (names, help text, bucket ladder) rendered
    as Prometheus text is pinned byte-for-byte."""
    registry = Registry()
    m = serving_metrics(registry)
    m["ttft"].observe(0.004)
    m["ttft"].observe(0.12)
    for _ in range(3):
        m["token_latency"].observe(0.0008)
    m["queue_depth"].set(2)
    m["active_slots"].set(3)
    m["pages_in_use"].set(12)
    m["tokens"].inc(42)
    m["requests"].inc(5)
    m["rejected"].inc(1)
    m["prefill_seconds"].observe(0.006)
    m["prefill_padded"].inc(13)
    m["decode_steps"].inc(17)
    m["spec_proposed"].inc(24)
    m["spec_accepted"].inc(19)
    m["hot_swaps"].inc(2)
    golden = open(os.path.join(GOLDEN, "serving_metrics.txt")).read()
    assert registry.to_prometheus(labels={"run_id": "fleet1234"}) == golden
    # get-or-create: a second call must hand back the same instruments
    assert serving_metrics(registry)["tokens"] is m["tokens"]


def test_flightdeck_scrape_and_generate_endpoint(lm, make_engine):
    """Acceptance: with the engine on the global registry and the exporter
    live, concurrent ``/generate`` calls answer with the greedy-reference
    tokens and the ``/metrics`` scrape carries non-empty SLO histograms."""
    module, params = lm
    server_mod.configure(0)
    addr = telemetry.flightdeck.ensure_server()
    engine = make_engine(module, params, num_slots=3, page_size=8,
                         registry=None)  # global registry -> the scrape
    install_http_endpoint(engine)

    rng = np.random.default_rng(5)
    prompts = [rng.integers(0, VOCAB, size=n).tolist() for n in (3, 5, 4)]
    refs = [_ref(module, params, p, 5) for p in prompts]
    replies = [None] * len(prompts)

    def call(i):
        status, text = _post(addr, "/generate",
                             {"prompt": prompts[i], "max_new_tokens": 5})
        replies[i] = (status, json.loads(text))

    threads = [threading.Thread(target=call, args=(i,))
               for i in range(len(prompts))]
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=120)
    for (status, body), ref in zip(replies, refs):
        assert status == 200 and body["tokens"] == ref
        assert body["finish_reason"] == "length"

    # GET with query parameters rides the same endpoint
    status, text = _get(addr, "/generate?prompt=1,2,3&max_new_tokens=2")
    assert status == 200 and len(json.loads(text)["tokens"]) == 2
    with pytest.raises(urllib.error.HTTPError) as err:
        _get(addr, "/generate?max_new_tokens=2")  # no prompt
    assert err.value.code == 400

    status, text = _get(addr, "/metrics")
    assert status == 200
    assert 'serving_ttft_seconds_bucket{' in text
    assert 'serving_token_latency_seconds_bucket{' in text
    for line in text.splitlines():
        if line.startswith('serving_ttft_seconds_count{run_id="servetest"}'):
            assert float(line.split()[-1]) >= 4  # 3 POST + 1 GET
            break
    else:
        pytest.fail("serving_ttft_seconds_count missing from scrape")
    assert 'serving_queue_depth{run_id="servetest"}' in text
    assert 'serving_tokens_total{run_id="servetest"}' in text


def test_model_predictor_routes_through_engine(lm, shared_engine):
    """``ModelPredictor(engine=...)``: frame rows become prompts; the
    prediction column carries the greedy continuations, token-identical
    to the per-request reference."""
    from distkeras_tpu.frame import DataFrame
    from distkeras_tpu.predictors import ModelPredictor

    module, params = lm
    engine = shared_engine
    rng = np.random.default_rng(6)
    prompts = rng.integers(0, VOCAB, size=(5, 4)).astype(np.int32)
    refs = [_ref(module, params, row.tolist(), 3) for row in prompts]

    predictor = ModelPredictor(engine=engine, max_new_tokens=3)
    out = predictor.predict(DataFrame({"features": prompts}))
    assert [list(v) for v in out.column("prediction")] == refs
    assert predictor.last_mode == "engine"
    with pytest.raises(TypeError, match="engine"):
        ModelPredictor()  # neither a model nor an engine


_SERVE_SCRIPT = """\
import json
import time

from distkeras_tpu import serving, telemetry

telemetry.flightdeck.activate()
with open("flags_out.json", "w") as f:
    json.dump(serving.serve_flags(), f)  # prove the env round-trip
time.sleep(120)  # a serving loop never exits; stop_serving terminates us
"""


def test_daemon_serve_verb_lifecycle(tmp_path, monkeypatch):
    """``serve`` launches a detached long-running job with the flightdeck
    forced on; ``serving_address`` discovers its exporter; engine knobs
    passed as ``Job.serve(flags=...)`` reach the child via
    ``DISTKERAS_SERVE_FLAGS`` / ``serving.serve_flags()`` and echo in the
    status reply; ``stop_serving`` terminates it and the status flips to
    ``stopped``."""
    from distkeras_tpu.job_deployment import Job, PunchcardServer

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    monkeypatch.setenv("PYTHONPATH", repo)
    server = PunchcardServer(port=0, secret="s3cret")
    server.start()
    flags = {"spec_tokens": 3, "prefill_buckets": [8, 32], "num_slots": 2}
    try:
        job = Job("127.0.0.1", server.port, secret="s3cret",
                  script=_SERVE_SCRIPT)
        assert job.serve(flags=flags)
        addr = job.serving_address(timeout=60)
        status, text = _get(addr, "/healthz")
        assert status == 200 and json.loads(text)["status"] == "ok"
        assert job.status()["serve_flags"] == flags
        flags_out = os.path.join(server.workdir, "flags_out.json")
        deadline = time.monotonic() + 30
        while not os.path.exists(flags_out) and time.monotonic() < deadline:
            time.sleep(0.05)
        with open(flags_out) as f:
            assert json.load(f) == flags  # the child saw the same knobs
        reply = job.stop_serving()
        assert reply == {"status": "stopped", "job_id": job.job_id}
        assert job.status()["status"] == "stopped"
    finally:
        server.stop()


def test_stop_aborts_in_flight_and_queued(lm, make_engine):
    module, params = lm
    engine = make_engine(module, params, num_slots=1, queue_size=8)
    pendings = [engine.submit(GenerateRequest(
        prompt=[1, 2, 3], max_new_tokens=20)) for _ in range(3)]
    engine.stop()
    results = [p.result(timeout=10) for p in pendings]
    assert all(r is not None for r in results)
    assert any(r.finish_reason == "aborted" for r in results)
    assert all(r.finish_reason in ("aborted", "length", "eos")
               for r in results)
