"""Unit tests for the dklint v3 dataflow engine (tools/dklint/dataflow.py):
CFG construction, reaching definitions, provenance (tainted_uses),
may_follow reachability, and the pinned no-false-positive corpus the v2
checkers needed baselines/disables for.  Pure AST work — no jax import."""

import ast
import os
import sys

import pytest

pytestmark = pytest.mark.lint

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from tools.dklint import analyze  # noqa: E402
from tools.dklint.dataflow import (  # noqa: E402
    FunctionFlow,
    edit_distance,
    expr_uses,
    function_flow,
    tainted_uses,
)


def _flow(src):
    """Parse ``src`` and build the flow for its first function."""
    tree = ast.parse(src)
    fn = next(n for n in ast.walk(tree)
              if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)))
    return FunctionFlow(fn)


def _uses_of(flow, name):
    """All Name loads of ``name`` registered in the flow, source order."""
    out = [u for u in flow._use_nodes.values() if u.id == name]
    out.sort(key=lambda n: (n.lineno, n.col_offset))
    return out


def _reaching_kinds(flow, use):
    return sorted(d.kind for d in flow.reaching(use))


# ---------------------------------------------------------- reaching defs

def test_param_reaches_until_rebound():
    flow = _flow(
        "def f(x):\n"
        "    a = x + 1\n"     # x reads the param
        "    x = 0\n"
        "    b = x + 2\n"     # x reads the rebind, not the param
        "    return a + b\n"
    )
    first, second = _uses_of(flow, "x")
    assert [d.kind for d in flow.reaching(first)] == ["param"]
    (d,) = flow.reaching(second)
    assert d.kind == "assign" and d.stmt.lineno == 3


def test_branch_join_merges_both_defs():
    flow = _flow(
        "def f(c):\n"
        "    if c:\n"
        "        v = 1\n"
        "    else:\n"
        "        v = 2\n"
        "    return v\n"
    )
    (use,) = _uses_of(flow, "v")
    assert sorted(d.stmt.lineno for d in flow.reaching(use)) == [3, 5]


def test_if_without_else_keeps_fallthrough_def():
    flow = _flow(
        "def f(c):\n"
        "    v = 0\n"
        "    if c:\n"
        "        v = 1\n"
        "    return v\n"
    )
    (use,) = _uses_of(flow, "v")
    assert sorted(d.stmt.lineno for d in flow.reaching(use)) == [2, 4]


def test_augmented_assign_reads_then_writes():
    flow = _flow(
        "def f(x):\n"
        "    x += 1\n"
        "    return x\n"
    )
    aug_read, ret_read = _uses_of(flow, "x")
    # the synthesized read inside `x += 1` sees the parameter ...
    assert [d.kind for d in flow.reaching(aug_read)] == ["param"]
    # ... and the return sees only the aug def, which strongly kills it
    (d,) = flow.reaching(ret_read)
    assert d.kind == "aug"


def test_loop_carried_defs_flow_around_the_back_edge():
    flow = _flow(
        "def f(n):\n"
        "    acc = 0\n"
        "    for i in range(n):\n"
        "        acc = acc + i\n"
        "    return acc\n"
    )
    body_read = _uses_of(flow, "acc")[0]   # `acc + i` inside the loop
    ret_read = _uses_of(flow, "acc")[-1]
    # first iteration reads the init, later ones the loop-carried assign
    assert sorted(d.stmt.lineno for d in flow.reaching(body_read)) == [2, 4]
    assert sorted(d.stmt.lineno for d in flow.reaching(ret_read)) == [2, 4]
    # the for target is a def of kind "for"
    (i_use,) = _uses_of(flow, "i")
    assert [d.kind for d in flow.reaching(i_use)] == ["for"]


def test_while_loop_carried_def():
    flow = _flow(
        "def f(x):\n"
        "    while x > 0:\n"
        "        x = x - 1\n"
        "    return x\n"
    )
    test_read = _uses_of(flow, "x")[0]
    assert sorted(d.kind for d in flow.reaching(test_read)) == [
        "assign", "param",
    ]


def test_try_except_join_sees_partial_body_state():
    flow = _flow(
        "def f(x):\n"
        "    v = 0\n"
        "    try:\n"
        "        v = risky(x)\n"
        "        v = v + 1\n"
        "    except ValueError:\n"
        "        pass\n"
        "    return v\n"
    )
    ret_read = _uses_of(flow, "v")[-1]
    # the exception may fire before either assignment, between them, or
    # not at all: all three defs reach the join
    assert sorted(d.stmt.lineno for d in flow.reaching(ret_read)) == [2, 4, 5]


def test_except_handler_binds_its_name():
    flow = _flow(
        "def f(x):\n"
        "    try:\n"
        "        return g(x)\n"
        "    except ValueError as e:\n"
        "        return str(e)\n"
    )
    (e_use,) = _uses_of(flow, "e")
    assert [d.kind for d in flow.reaching(e_use)] == ["except"]


def test_walrus_binds_inside_the_test():
    flow = _flow(
        "def f(xs):\n"
        "    if (n := len(xs)) > 3:\n"
        "        return n\n"
        "    return 0\n"
    )
    (n_use,) = _uses_of(flow, "n")
    assert [d.kind for d in flow.reaching(n_use)] == ["walrus"]


def test_walrus_in_else_arm_does_not_reach_the_if_body():
    # only the head expression's walruses belong to the head node: a
    # binding inside the else arm must not flow into the (exclusive) if
    # body, where the name is still unbound
    flow = _flow(
        "def f(xs, flag):\n"
        "    if flag:\n"
        "        return m\n"
        "    else:\n"
        "        return (m := len(xs))\n"
    )
    (m_use,) = _uses_of(flow, "m")
    assert flow.reaching(m_use) == ()


def test_walrus_in_loop_body_gen_at_its_own_statement_not_the_head():
    flow = _flow(
        "def f(xs):\n"
        "    for x in xs:\n"
        "        y = (w := g(x))\n"
        "    return w\n"
    )
    (w_use,) = _uses_of(flow, "w")
    defs = flow.reaching(w_use)
    assert [d.kind for d in defs] == ["walrus"]
    # attributed to the assignment on line 3, not the for head on line 2
    assert [d.stmt.lineno for d in defs] == [3]


def test_walrus_in_raise_reaches_the_handler():
    flow = _flow(
        "def f(x):\n"
        "    try:\n"
        "        raise Err((v := g(x)))\n"
        "    except Err:\n"
        "        return v\n"
    )
    (v_use,) = _uses_of(flow, "v")
    assert [d.kind for d in flow.reaching(v_use)] == ["walrus"]


def test_free_variables_have_no_reaching_defs():
    flow = _flow(
        "def f(x):\n"
        "    return x + CONST\n"
    )
    (const_use,) = _uses_of(flow, "CONST")
    assert flow.reaching(const_use) == ()
    assert flow.is_use(const_use)


# ------------------------------------------------------------- provenance

def test_taint_propagates_through_assignment_chains():
    flow = _flow(
        "def f(x):\n"
        "    y = x * 2\n"
        "    z = y + 1\n"
        "    return z\n"
    )
    tainted = tainted_uses(flow, ["x"])
    (z_use,) = _uses_of(flow, "z")
    assert id(z_use) in tainted


def test_rebinding_to_constant_clears_taint():
    flow = _flow(
        "def f(x):\n"
        "    x = 0.0\n"
        "    return float(x)\n"
    )
    tainted = tainted_uses(flow, ["x"])
    ret_read = _uses_of(flow, "x")[-1]
    assert id(ret_read) not in tainted


def test_partial_rebind_keeps_taint_on_the_join():
    flow = _flow(
        "def f(x, c):\n"
        "    if c:\n"
        "        x = 0\n"
        "    return x\n"
    )
    tainted = tainted_uses(flow, ["x"])
    ret_read = _uses_of(flow, "x")[-1]
    assert id(ret_read) in tainted  # the param still reaches one path


def test_free_variables_never_taint():
    flow = _flow(
        "def f(x):\n"
        "    y = CONST + 1\n"
        "    return y\n"
    )
    tainted = tainted_uses(flow, ["x"])
    (y_use,) = _uses_of(flow, "y")
    assert id(y_use) not in tainted


def test_loop_carried_taint():
    flow = _flow(
        "def f(x, n):\n"
        "    acc = 0\n"
        "    for _ in range(n):\n"
        "        acc = acc + x\n"
        "    return acc\n"
    )
    tainted = tainted_uses(flow, ["x"])
    ret_read = _uses_of(flow, "acc")[-1]
    assert id(ret_read) in tainted


# ------------------------------------------------------------- may_follow

def test_may_follow_sequential_and_exclusive():
    flow = _flow(
        "def f(key, c):\n"
        "    a = split(key)\n"
        "    if c:\n"
        "        b = uniform(key)\n"
        "    else:\n"
        "        d = normal(key)\n"
        "    return a\n"
    )
    seq_a, arm_b, arm_d = _uses_of(flow, "key")
    assert flow.may_follow(seq_a, arm_b)       # straight line
    assert flow.may_follow(seq_a, arm_d)
    assert not flow.may_follow(arm_b, arm_d)   # exclusive if/else arms
    assert not flow.may_follow(arm_d, arm_b)


def test_may_follow_loop_back_edge():
    flow = _flow(
        "def f(key, n):\n"
        "    for _ in range(n):\n"
        "        u = uniform(key)\n"
        "    return u\n"
    )
    (key_use,) = _uses_of(flow, "key")
    # an iteration's consumption precedes the next iteration's: the back
    # edge makes a use follow itself
    assert flow.may_follow(key_use, key_use)


def test_may_follow_early_return_blocks_later_use():
    flow = _flow(
        "def f(key, c):\n"
        "    if c:\n"
        "        return uniform(key)\n"
        "    return normal(key)\n"
    )
    first, second = _uses_of(flow, "key")
    assert not flow.may_follow(first, second)  # first path returned already


def test_may_follow_try_finally_exits():
    """Lockset correctness across release-on-exception paths: the finally
    suite follows both the try body and every handler, and code after the
    try follows the finally."""
    flow = _flow(
        "def f(key, c):\n"
        "    try:\n"
        "        a = uniform(key)\n"
        "    except ValueError:\n"
        "        b = normal(key)\n"
        "    finally:\n"
        "        c = fold_in(key)\n"
        "    return split(key)\n"
    )
    body_use, handler_use, finally_use, after_use = _uses_of(flow, "key")
    assert flow.may_follow(body_use, handler_use)    # body may raise into it
    assert flow.may_follow(body_use, finally_use)
    assert flow.may_follow(handler_use, finally_use)
    assert flow.may_follow(finally_use, after_use)
    assert not flow.may_follow(handler_use, body_use)
    assert not flow.may_follow(finally_use, body_use)


def test_may_follow_handlers_are_exclusive_siblings():
    """Handler A's fallout never reaches handler B — they are alternative
    catches of the same body, not a chain."""
    flow = _flow(
        "def f(key):\n"
        "    try:\n"
        "        a = uniform(key)\n"
        "    except ValueError:\n"
        "        b = normal(key)\n"
        "    except KeyError:\n"
        "        c = bernoulli(key)\n"
        "    return a\n"
    )
    body_use, first_handler, second_handler = _uses_of(flow, "key")
    assert flow.may_follow(body_use, first_handler)
    assert flow.may_follow(body_use, second_handler)
    assert not flow.may_follow(first_handler, second_handler)
    assert not flow.may_follow(second_handler, first_handler)


def test_may_follow_with_suite_exit():
    """Code after a with-block follows the suite body — the context exit
    is a fall-through, not a barrier (this is what lets a lockset drop
    back to the pre-acquire set after the suite)."""
    flow = _flow(
        "def f(key, lk):\n"
        "    with lk:\n"
        "        a = uniform(key)\n"
        "    return normal(key)\n"
    )
    inside, after = _uses_of(flow, "key")
    assert flow.may_follow(inside, after)
    assert not flow.may_follow(after, inside)


def test_may_follow_return_bypasses_finally_ordering():
    """A Return inside try exits via the CFG's exit node: a use *after*
    the whole try/finally statement is unreachable from it."""
    flow = _flow(
        "def f(key, c):\n"
        "    try:\n"
        "        if c:\n"
        "            return uniform(key)\n"
        "    finally:\n"
        "        pass\n"
        "    return normal(key)\n"
    )
    returned, after = _uses_of(flow, "key")
    assert not flow.may_follow(returned, after)


# ------------------------------------------------------------ small tools

def test_expr_uses_skips_nested_lambda_bodies():
    expr = ast.parse("f(x, lambda v: v + y, [z for z in w])", mode="eval").body
    names = [n.id for n in expr_uses(expr)]
    assert "x" in names and "w" in names
    assert "y" not in names  # lambda body is deferred
    assert "v" not in names


def test_function_flow_cache_reuses_instances():
    tree = ast.parse("def f(x):\n    return x\n")
    fn = tree.body[0]
    cache = {}
    assert function_flow(fn, cache) is function_flow(fn, cache)


def test_edit_distance_basics_and_cap():
    assert edit_distance("abc", "abc") == 0
    assert edit_distance("serving_widget_total", "serving_widgets_total") == 1
    assert edit_distance("abc", "axc") == 1
    assert edit_distance("abc", "xyzzy", cap=3) == 3
    assert edit_distance("short", "a_very_long_name", cap=3) == 3


# ------------------------------------------- pinned no-false-positive corpus
#
# Shapes that v2's flat name matching flagged (or needed inline disables
# for) and v3 provenance proves clean.  Each is a miniature module run
# through the real checkers; the assertion is zero findings.

_NO_FP_CORPUS = [
    # parameter rebound to a host constant before the sync
    (
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    x = 0.0\n"
        "    return float(x)\n",
        ["DK101"],
    ),
    # closure constant synced inside a jitted factory product — the
    # test_sanitizer.py pattern that carried `# dklint: disable=DK101`
    (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "def make_step(scale):\n"
        "    const = jnp.float32(scale)\n"
        "    @jax.jit\n"
        "    def step(x):\n"
        "        return x * const.item()\n"
        "    return step\n",
        ["DK101"],
    ),
    # branch on a parameter rebound to a host int
    (
        "import jax\n"
        "def g(x):\n"
        "    x = 0\n"
        "    if x > 0:\n"
        "        return 1\n"
        "    return 0\n"
        "gj = jax.jit(g)\n",
        ["DK109"],
    ),
    # aug-assign of a host accumulator seeded from a constant
    (
        "import jax\n"
        "@jax.jit\n"
        "def h(x):\n"
        "    n = 0\n"
        "    n += 1\n"
        "    return x, float(n)\n",
        ["DK101", "DK109"],
    ),
]


@pytest.mark.parametrize("src,select", _NO_FP_CORPUS,
                         ids=["rebound-sync", "closure-const", "rebound-branch",
                              "aug-host-acc"])
def test_no_false_positive_corpus(tmp_path, src, select):
    p = tmp_path / "mod.py"
    p.write_text(src)
    findings, _ = analyze([str(p)], root=str(tmp_path), select=select)
    assert findings == [], [f.render() for f in findings]


def test_true_positives_still_fire(tmp_path):
    """The dual of the corpus: derivation through arithmetic keeps the
    taint, so the migration didn't just silence the rules."""
    src = (
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    y = x * 2\n"
        "    return float(y)\n"
    )
    p = tmp_path / "mod.py"
    p.write_text(src)
    findings, _ = analyze([str(p)], root=str(tmp_path), select=["DK101"])
    assert [(f.rule, f.line) for f in findings] == [("DK101", 5)]
