"""``tools.dktrace`` tests: merging per-process Chrome traces into one fleet
timeline — deterministic synthetic golden, dispatch-window anchoring, label
and metadata layout, CLI exit codes, and an end-to-end run where two daemon
jobs' traces merge with the daemon's own into a single Perfetto-loadable
timeline sharing one run_id."""

import json
import os
import subprocess
import sys

import pytest

from distkeras_tpu import telemetry
from distkeras_tpu.job_deployment import Job, PunchcardServer
from distkeras_tpu.telemetry.flightdeck import correlate
from tools.dktrace import merge_trace_dirs
from tools.dktrace.__main__ import main as dktrace_main

GOLDEN = os.path.join(os.path.dirname(os.path.abspath(__file__)), "golden")


def _ev(name, ts, dur, pid, args):
    return {"name": name, "cat": "distkeras", "ph": "X", "ts": ts, "dur": dur,
            "pid": pid, "tid": 1, "args": args}


# One daemon that dispatched two jobs: job-a at ts 1000 on the daemon's
# axis, job-b at ts 5000.  Each job's own trace starts near its process-local
# origin (ts 50 / 80) — the merge must land them inside their dispatch
# windows.  All values hand-picked so the merged output is byte-stable.
DAEMON_EVENTS = [
    _ev("job_run", 1000.0, 3000.0, 100,
        {"job_id": "job-a", "run_id": "fleet1234"}),
    _ev("job_run", 5000.0, 2500.0, 100,
        {"job_id": "job-b", "run_id": "fleet1234"}),
]
JOB_A_EVENTS = [
    _ev("epoch", 50.0, 2000.0, 201, {"epoch": 0, "run_id": "fleet1234"}),
    _ev("window", 60.0, 500.0, 201,
        {"parent": "epoch", "run_id": "fleet1234"}),
]
JOB_B_EVENTS = [
    _ev("epoch", 80.0, 1800.0, 202, {"epoch": 0, "run_id": "fleet1234"}),
]


def _write_trace(directory, fname, events):
    os.makedirs(directory, exist_ok=True)
    with open(os.path.join(directory, fname), "w") as fh:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, fh)


def fleet_dirs(root):
    """The synthetic fleet the golden file pins: daemon + two jobs."""
    d = os.path.join(str(root), "daemon")
    a = os.path.join(str(root), "job-a")
    b = os.path.join(str(root), "job-b")
    _write_trace(d, "trace_100.json", DAEMON_EVENTS)
    _write_trace(a, "trace_201.json", JOB_A_EVENTS)
    _write_trace(b, "trace_202.json", JOB_B_EVENTS)
    return [d, a, b]


# ----------------------------------------------------------------- merging

def test_merge_matches_golden(tmp_path):
    merged = merge_trace_dirs(fleet_dirs(tmp_path))
    golden = json.load(open(os.path.join(GOLDEN, "dktrace_merge.json")))
    assert merged == golden


def test_merge_anchors_jobs_inside_their_dispatch_windows(tmp_path):
    merged = merge_trace_dirs(fleet_dirs(tmp_path))
    evs = merged["traceEvents"]
    by_pid = {}
    for e in evs:
        if e.get("ph") == "M":
            by_pid[e["args"]["name"]] = e["pid"]
    assert by_pid == {"daemon": 1, "job-a": 2, "job-b": 3}

    runs = {e["args"]["job_id"]: e for e in evs if e.get("name") == "job_run"}
    epochs = {e["pid"]: e for e in evs if e.get("name") == "epoch"}
    # daemon axis is the merged origin: its first dispatch starts at 0
    assert runs["job-a"]["ts"] == 0.0
    assert runs["job-b"]["ts"] == 4000.0
    # each job's first event lands exactly at the start of its dispatch span
    assert epochs[by_pid["job-a"]]["ts"] == runs["job-a"]["ts"]
    assert epochs[by_pid["job-b"]]["ts"] == runs["job-b"]["ts"]
    # intra-job spacing is preserved (window started 10us after epoch)
    window = next(e for e in evs if e.get("name") == "window")
    assert window["ts"] - epochs[by_pid["job-a"]]["ts"] == pytest.approx(10.0)
    assert merged["otherData"] == {
        "run_ids": ["fleet1234"],
        "processes": ["daemon", "job-a", "job-b"],
    }


def test_merge_unmatched_dir_normalises_to_zero(tmp_path):
    solo = os.path.join(str(tmp_path), "solo")
    _write_trace(solo, "trace_9.json",
                 [_ev("epoch", 777.0, 10.0, 9, {"run_id": "r1"})])
    merged = merge_trace_dirs([solo])
    ep = next(e for e in merged["traceEvents"] if e["name"] == "epoch")
    assert (ep["ts"], ep["pid"]) == (0.0, 1)


def test_merge_labels_multi_process_dirs(tmp_path):
    d = os.path.join(str(tmp_path), "host")
    _write_trace(d, "trace_11.json", [_ev("a", 0.0, 1.0, 11, {})])
    _write_trace(d, "trace_22.json", [_ev("b", 0.0, 1.0, 22, {})])
    merged = merge_trace_dirs([d])
    labels = [e["args"]["name"] for e in merged["traceEvents"]
              if e.get("ph") == "M"]
    assert labels == ["host/11", "host/22"]


def test_merge_without_traces_raises(tmp_path):
    with pytest.raises(ValueError, match="no trace"):
        merge_trace_dirs([str(tmp_path)])


def test_merge_rejects_corrupt_trace(tmp_path):
    d = os.path.join(str(tmp_path), "bad")
    os.makedirs(d)
    open(os.path.join(d, "trace_1.json"), "w").write("{not json")
    with pytest.raises(ValueError, match="unreadable"):
        merge_trace_dirs([d])


# --------------------------------------------------------------------- CLI

def test_cli_merge_writes_perfetto_loadable_output(tmp_path, capsys):
    out = str(tmp_path / "merged.json")
    assert dktrace_main(["merge", *fleet_dirs(tmp_path), "-o", out]) == 0
    payload = json.load(open(out))
    assert payload == merge_trace_dirs(fleet_dirs(tmp_path))
    cap = capsys.readouterr()
    assert cap.out == ""  # the trace goes to the file, not the terminal
    assert "5 events across 3 processes" in cap.err


def test_cli_merge_stdout_and_exit_codes(tmp_path, capsys):
    dirs = fleet_dirs(tmp_path)
    assert dktrace_main(["merge", *dirs]) == 0
    cap = capsys.readouterr()
    assert json.loads(cap.out)["otherData"]["run_ids"] == ["fleet1234"]
    assert cap.err == ""  # single run_id: no warning

    empty = str(tmp_path / "empty")
    os.makedirs(empty)
    assert dktrace_main(["merge", empty]) == 2
    assert "no trace" in capsys.readouterr().err


def test_cli_warns_on_mixed_run_ids(tmp_path, capsys):
    a = os.path.join(str(tmp_path), "a")
    b = os.path.join(str(tmp_path), "b")
    _write_trace(a, "trace_1.json", [_ev("x", 0.0, 1.0, 1, {"run_id": "r1"})])
    _write_trace(b, "trace_2.json", [_ev("y", 0.0, 1.0, 2, {"run_id": "r2"})])
    assert dktrace_main(["merge", a, b, "-o",
                         str(tmp_path / "out.json")]) == 0
    assert "2 distinct run_ids" in capsys.readouterr().err


def test_cli_runs_as_module(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = str(tmp_path / "merged.json")
    proc = subprocess.run(
        [sys.executable, "-m", "tools.dktrace", "merge",
         *fleet_dirs(tmp_path), "-o", out],
        capture_output=True, text=True, cwd=repo, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    assert json.load(open(out))["otherData"]["run_ids"] == ["fleet1234"]


# -------------------------------------------------------------- end to end

_TRACE_JOB = """\
from distkeras_tpu import telemetry

with telemetry.trace.span("epoch", epoch=0):
    with telemetry.trace.span("window"):
        pass
telemetry.flush()
"""


def test_two_daemon_jobs_merge_into_one_fleet_timeline(tmp_path, monkeypatch):
    """Acceptance: two jobs run under a daemon; ``dktrace merge`` over the
    daemon's dir and both job dirs yields one timeline with three distinct
    process names and every span stamped with the same fleet run_id."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    monkeypatch.setenv("PYTHONPATH", repo)
    monkeypatch.setenv("DISTKERAS_TELEMETRY_DIR", str(tmp_path))
    telemetry.configure(True)
    telemetry.trace.reset()
    telemetry.metrics.reset()
    correlate.set_run_id("fleetrun")
    server = PunchcardServer(port=0, secret="s3cret")
    server.start()
    try:
        job_dirs = []
        for _ in range(2):
            job = Job("127.0.0.1", server.port, secret="s3cret",
                      script=_TRACE_JOB)
            job.submit()
            st = job.wait(timeout=120)
            assert st["status"] == "finished", st.get("output")
            job_dirs.append(st["telemetry_dir"])
    finally:
        server.stop()  # flushes the daemon's own trace into tmp_path
        telemetry.trace.reset()
        telemetry.metrics.reset()
        correlate.set_run_id(None)
        telemetry.configure(None)

    merged = merge_trace_dirs([str(tmp_path), *job_dirs])
    json.dumps(merged)  # Perfetto-loadable: plain JSON through and through
    names = [e["args"]["name"] for e in merged["traceEvents"]
             if e.get("ph") == "M"]
    assert len(names) == 3 and len(set(names)) == 3

    epochs = [e for e in merged["traceEvents"] if e.get("name") == "epoch"]
    runs = {e["args"]["job_id"]: e for e in merged["traceEvents"]
            if e.get("name") == "job_run"}
    assert len(epochs) == 2 and len(runs) == 2
    rids = {e["args"]["run_id"] for e in epochs}
    rids |= {e["args"]["run_id"] for e in runs.values()}
    assert rids == {"fleetrun"}
    assert merged["otherData"]["run_ids"] == ["fleetrun"]
    # anchoring: each job's epoch sits inside its daemon-side dispatch window
    for e in epochs:
        base = os.path.basename(
            job_dirs[e["pid"] - 2])  # pids follow input order: daemon is 1
        w = runs[base]
        assert w["ts"] <= e["ts"] <= w["ts"] + w["dur"]
