"""KV-cached greedy decode: cached and recompute paths emit IDENTICAL tokens.

The cache pads K/V to max_len and masks the unwritten tail to exp(-inf) = 0,
so each step's logits equal the full-context recompute's last-position
logits; greedy argmax must therefore match token for token.  Also pinned:
the batched output shape (prompt included), cache-capacity validation, and
the trainer-returned TrainedModel as the entry point.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import distkeras_tpu as dk
from distkeras_tpu.models import (
    FlaxModel, StagedLM, TransformerLM, greedy_generate,
)
from distkeras_tpu.models.generate import greedy_generate_module

VOCAB, SEQ = 23, 16


def _corpus(n=128, seed=0):
    rng = np.random.default_rng(seed)
    start = rng.integers(0, VOCAB, size=(n, 1))
    x = ((start + np.arange(SEQ)) % VOCAB).astype(np.int32)
    return x, ((x + 1) % VOCAB).astype(np.int32)


def _recompute(model, ctx, steps):
    ctx = np.asarray(ctx, np.int32)
    for _ in range(steps):
        nxt = np.argmax(np.asarray(model(ctx))[:, -1], -1)[:, None]
        ctx = np.concatenate([ctx, nxt.astype(np.int32)], axis=1)
    return ctx


def _train(model, **kw):
    x, y = _corpus()
    t = dk.DOWNPOUR(model, loss="token_crossentropy",
                    metrics=("token_accuracy",),
                    worker_optimizer=("adam", {"learning_rate": 2e-3}),
                    num_workers=4, batch_size=16, num_epoch=3,
                    communication_window=2, **kw)
    return t.train(dk.from_numpy(x, y)), x


def test_kv_cache_matches_recompute_transformer_lm():
    trained, x = _train(FlaxModel(TransformerLM(
        vocab_size=VOCAB, dim=32, heads=2, num_layers=2, max_len=64)))
    prompt = x[:4, :8]
    ref = _recompute(trained, prompt, 6)
    out = greedy_generate(trained, prompt, 6)
    assert out.shape == (4, 14) and out.dtype == np.int32
    np.testing.assert_array_equal(out, ref)
    np.testing.assert_array_equal(out[:, :8], prompt)  # prompt preserved


def test_kv_cache_matches_recompute_staged_lm():
    trained, x = _train(
        StagedLM(vocab_size=VOCAB, dim=32, heads=2, num_stages=2,
                 blocks_per_stage=1, max_len=64),
        pipeline_stages=2,
    )
    prompt = x[:4, :8]
    np.testing.assert_array_equal(
        greedy_generate(trained, prompt, 6), _recompute(trained, prompt, 6)
    )


def test_untrained_module_path_and_validation():
    """The module-level entry works on raw params, and capacity/shape errors
    are loud (the cache is sized to max_len)."""
    module = TransformerLM(vocab_size=VOCAB, dim=16, heads=2, num_layers=1,
                           max_len=16)
    prompt = np.zeros((2, 8), np.int32)
    params = module.init(jax.random.PRNGKey(0), jnp.asarray(prompt))["params"]
    out = greedy_generate_module(module, params, prompt, 8)
    assert out.shape == (2, 16)
    with pytest.raises(ValueError, match="max_len"):
        greedy_generate_module(module, params, prompt, 9)
    with pytest.raises(ValueError, match="batch"):
        greedy_generate_module(module, params, prompt[0], 2)
    np.testing.assert_array_equal(
        greedy_generate_module(module, params, prompt, 0), prompt
    )


def test_generate_rejects_non_lm():
    from distkeras_tpu.models import MLP

    x = np.random.default_rng(0).normal(size=(64, 8)).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.int32)
    t = dk.SingleTrainer(FlaxModel(MLP(features=(8,), num_classes=2)),
                         worker_optimizer=("sgd", {"learning_rate": 0.1}),
                         batch_size=16, num_epoch=1)
    trained = t.train(dk.from_numpy(x, np.eye(2, dtype=np.float32)[y]))
    with pytest.raises(TypeError, match="decode"):
        greedy_generate(trained, np.zeros((1, 4), np.int32), 2)


def test_generate_rejects_classifier_by_name():
    """TransformerClassifier has max_len but no decode support: the guard
    must reject it with the named error, not a flax TypeError from deep
    inside apply."""
    from distkeras_tpu.models import TransformerClassifier
    from distkeras_tpu.models.adapter import TrainedModel

    module = TransformerClassifier(vocab_size=VOCAB, num_classes=2, dim=16,
                                   heads=2, num_layers=1, max_len=16)
    adapter = FlaxModel(module)
    params, state = adapter.init(jax.random.PRNGKey(0),
                                 np.zeros((2, 8), np.int32))
    trained = TrainedModel(adapter, params, state)
    with pytest.raises(TypeError, match="KV-cache decode"):
        greedy_generate(trained, np.zeros((2, 8), np.int32), 2)


def test_generate_program_is_cached_across_calls():
    """Repeat calls with the same (module, steps, shapes) must reuse the
    compiled decode program (serving-shaped: no per-request recompile)."""
    from distkeras_tpu.models import generate as gen_mod

    module = TransformerLM(vocab_size=VOCAB, dim=16, heads=2, num_layers=1,
                           max_len=16)
    prompt = np.zeros((2, 8), np.int32)
    params = module.init(jax.random.PRNGKey(0), jnp.asarray(prompt))["params"]
    out1 = greedy_generate_module(module, params, prompt, 4)
    key = (id(module), 4)
    assert key in gen_mod._DECODE_PROGRAMS
    cached = gen_mod._DECODE_PROGRAMS[key][1]
    misses_before = cached._cache_size()
    out2 = greedy_generate_module(module, params, prompt, 4)
    assert cached._cache_size() == misses_before  # no retrace
    np.testing.assert_array_equal(out1, out2)
