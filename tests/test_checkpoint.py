"""Checkpoint/resume tests: exact state round-trip and trainer resume."""

import jax
import numpy as np

import distkeras_tpu as dk
from distkeras_tpu.checkpoint import CheckpointManager, latest_step, restore_checkpoint, save_checkpoint
from distkeras_tpu.frame import from_numpy
from distkeras_tpu.models import MLP, FlaxModel


def test_pytree_roundtrip(tmp_path):
    state = {"w": np.arange(6, dtype=np.float32).reshape(2, 3),
             "nested": {"step": np.asarray(7)}}
    save_checkpoint(str(tmp_path), state, 3)
    assert latest_step(str(tmp_path)) == 3
    restored = restore_checkpoint(str(tmp_path), like=state)
    np.testing.assert_array_equal(restored["w"], state["w"])
    assert int(restored["nested"]["step"]) == 7


def test_manager_gc(tmp_path):
    mgr = CheckpointManager(str(tmp_path), every=1, keep=2)
    state = {"x": np.zeros(2)}
    for epoch in range(5):
        mgr.maybe_save(state, epoch)
    assert mgr.latest() == 5
    import os

    found = sorted(d for d in os.listdir(tmp_path) if d.startswith("step_"))
    assert found == ["step_4", "step_4.manifest.json",
                     "step_5", "step_5.manifest.json"]


def test_trainer_resume_matches_uninterrupted(toy_classification, tmp_path):
    """Train 4 epochs straight vs 2 epochs + resume 2 more: identical params."""
    x, y, onehot = toy_classification
    df = from_numpy(x, onehot)

    def trainer(num_epoch, resume=False):
        return dk.DOWNPOUR(FlaxModel(MLP(features=(16,), num_classes=2)),
                           loss="categorical_crossentropy",
                           worker_optimizer=("sgd", {"learning_rate": 0.05}),
                           num_workers=4, batch_size=16, num_epoch=num_epoch,
                           communication_window=4, seed=11,
                           checkpoint_dir=str(tmp_path), checkpoint_every=1,
                           resume=resume)

    straight = dk.DOWNPOUR(FlaxModel(MLP(features=(16,), num_classes=2)),
                           loss="categorical_crossentropy",
                           worker_optimizer=("sgd", {"learning_rate": 0.05}),
                           num_workers=4, batch_size=16, num_epoch=4,
                           communication_window=4, seed=11).train(df)

    trainer(2).train(df)                   # writes checkpoints at epochs 1,2
    resumed = trainer(4, resume=True).train(df)  # resumes from epoch 2

    for a, b in zip(jax.tree.leaves(straight.params), jax.tree.leaves(resumed.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7)


def test_pipeline_resume_matches_uninterrupted(tmp_path):
    """Checkpoint/resume under pipeline parallelism: the stage-sharded
    TrainState round-trips through Orbax bit-exactly (4 epochs straight ==
    2 epochs + resume 2 more)."""
    from distkeras_tpu.models import StagedTransformer

    rng = np.random.default_rng(3)
    x = rng.integers(0, 50, size=(128, 16)).astype(np.int32)
    y = ((x == 7).sum(1) > (x == 3).sum(1)).astype(np.int32)
    df = from_numpy(x, np.eye(2, dtype=np.float32)[y])

    def model():
        return StagedTransformer(vocab_size=50, num_classes=2, dim=16,
                                 heads=2, num_stages=4, blocks_per_stage=1,
                                 max_len=32)

    def trainer(num_epoch, ckpt=None, resume=False):
        return dk.DOWNPOUR(model(), loss="categorical_crossentropy",
                           worker_optimizer=("sgd", {"learning_rate": 0.05}),
                           num_workers=2, batch_size=16, num_epoch=num_epoch,
                           communication_window=2, seed=11,
                           pipeline_stages=4,
                           checkpoint_dir=ckpt, checkpoint_every=1,
                           resume=resume)

    straight = trainer(4).train(df)
    trainer(2, ckpt=str(tmp_path)).train(df)
    resumed = trainer(4, ckpt=str(tmp_path), resume=True).train(df)

    for a, b in zip(jax.tree.leaves(straight.params),
                    jax.tree.leaves(resumed.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_gc_never_deletes_the_only_committed_step(tmp_path):
    """keep=1 with an async save in flight: the in-flight step must not
    count toward `keep`, or _gc deletes the only published checkpoint and
    a crash during the in-flight save leaves nothing restorable."""
    import os

    from distkeras_tpu.checkpoint import write_manifest

    mgr = CheckpointManager(str(tmp_path), every=1, keep=1)
    state = {"x": np.zeros(2)}
    mgr.maybe_save(state, 0)
    mgr.wait()  # step_1 committed + published
    assert sorted(d for d in os.listdir(tmp_path) if d.startswith("step_")) \
        == ["step_1", "step_1.manifest.json"]
    # simulate step 2 in flight: initiated (in _saved) but no final dir yet
    mgr._saved.add(2)
    mgr._gc()
    assert "step_1" in os.listdir(tmp_path), (
        "in-flight step must not evict the only published checkpoint"
    )
    # step 2's orbax dir landing is NOT enough: unpublished steps are
    # invisible to the keep policy (and must never be deleted themselves)
    os.makedirs(tmp_path / "step_2")
    mgr._gc()
    assert "step_1" in os.listdir(tmp_path), (
        "an unpublished (manifest-less) step must not evict its predecessor"
    )
    # once step 2 PUBLISHES (manifest commits), the predecessor is collectable
    write_manifest(str(tmp_path), 2)
    mgr._gc()
    assert sorted(d for d in os.listdir(tmp_path) if d.startswith("step_")) \
        == ["step_2", "step_2.manifest.json"]
