"""Pipeline x sequence parallelism: long context on the pod mesh.

The last composition hole: ring attention previously lived only in the
WindowedEngine's (workers, seq) mesh, the microbatch pipeline only in
(workers, stages).  ``PipelineEngine(seq_shards=k)`` runs both in one
(workers, stages, seq) mesh, ALL axes manual: tokens/labels shard over
``seq``, the staged blocks (built with ``seq_axis``) run ring attention
inside every pipeline tick, positions offset by the seq-block index, and
every gradient gets a seq-axis pmean on top of the stage-axis sync.
Sharding is layout, not math — trajectories must match the 2-axis pipeline
within ring-attention's float-reassociation tolerance (the same class the
WindowedEngine sp tests use).
"""

import jax
import numpy as np
import pytest

from distkeras_tpu.algorithms import Downpour
from distkeras_tpu.models import StagedLM, StagedTransformer
from distkeras_tpu.parallel import PipelineEngine
from distkeras_tpu.parallel.mesh import SEQ_AXIS

from conftest import epoch_data, toy_text


def _staged(seq=True, fsdp_ok=True, **kw):
    return StagedTransformer(
        vocab_size=50, num_classes=2, dim=32, heads=2,
        num_stages=2, blocks_per_stage=1, max_len=64,
        seq_axis=SEQ_AXIS if seq else None, **kw,
    )


def _engine(adapter, *, seq_shards=1, fsdp=False, devices=None,
            loss="categorical_crossentropy",
            optimizer=("sgd", {"learning_rate": 0.05})):
    if devices is None:
        devices = jax.devices()[: 2 * 2 * seq_shards]
    return PipelineEngine(
        adapter, loss, optimizer, Downpour(2),
        num_workers=2, microbatches=2, metrics=(),
        seq_shards=seq_shards, fsdp=fsdp, devices=devices,
    )


def _run(engine, xs, ys, epochs=3):
    xs_d, ys_d = engine.shard_batches(xs, ys)
    state = engine.init_state(jax.random.PRNGKey(0), xs[0, 0, 0])
    losses = []
    for _ in range(epochs):
        state, stats = engine.run_epoch(state, xs_d, ys_d)
        losses.append(np.asarray(stats["loss"]))
    return engine.gather_center(state), np.concatenate(losses), state


def test_pp_sp_trajectory_matches_pp():
    """2 workers x 2 stages x 2 seq == 2 workers x 2 stages: ring attention
    + block-offset positions + seq-pmean grad sync reproduce the unsharded
    math (float-reassociation tolerance)."""
    x, _, onehot = toy_text()
    xs, ys = epoch_data(x, onehot, num_workers=2, n_windows=2, window=2, batch=8)

    center_sp, loss_sp, _ = _run(_engine(_staged(True), seq_shards=2), xs, ys)
    center_pp, loss_pp, _ = _run(_engine(_staged(False)), xs, ys)

    np.testing.assert_allclose(loss_sp, loss_pp, rtol=2e-4, atol=2e-5)
    for a, b in zip(jax.tree.leaves(center_sp), jax.tree.leaves(center_pp)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-3, atol=3e-4)


def test_pp_sp_fsdp_trajectory_matches_pp_sp():
    """All three: stage-sharded embed/head on the (workers, stages, seq)
    mesh — fsdp is layout only, so the trajectory equals pp x sp exactly
    (no new float reassociation: the gather reconstructs the same values)."""
    x, _, onehot = toy_text()
    xs, ys = epoch_data(x, onehot, num_workers=2, n_windows=2, window=2, batch=8)

    center_f, loss_f, state = _run(
        _engine(_staged(True), seq_shards=2, fsdp=True), xs, ys)
    center_r, loss_r, _ = _run(_engine(_staged(True), seq_shards=2), xs, ys)

    np.testing.assert_allclose(loss_f, loss_r, rtol=2e-5, atol=1e-6)
    for a, b in zip(jax.tree.leaves(center_f), jax.tree.leaves(center_r)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=1e-6)
    # the fsdp layout is real on the 3-axis mesh
    tok = state.center_params["embed"]["tok_embed"]["embedding"]
    assert tok.addressable_shards[0].data.shape == (25, 32)


def test_pp_sp_causal_lm_trains():
    """StagedLM with causal RING attention through the pipeline: per-token
    labels shard over the seq axis with the tokens; the loss falls."""
    rng = np.random.default_rng(0)
    x = rng.integers(0, 32, size=(128, 16)).astype(np.int32)
    xs, ys = epoch_data(x, x, num_workers=2, n_windows=2, window=2, batch=8)
    ys = ys.astype(np.int32)
    adapter = StagedLM(vocab_size=32, dim=32, heads=2, num_stages=2,
                       blocks_per_stage=1, max_len=16, seq_axis=SEQ_AXIS)
    eng = _engine(adapter, seq_shards=2, loss="token_crossentropy",
                  optimizer=("adam", {"learning_rate": 2e-3}))
    xs_d, ys_d = eng.shard_batches(xs, ys)
    state = eng.init_state(jax.random.PRNGKey(0), xs[0, 0, 0])
    losses = []
    for _ in range(6):
        state, stats = eng.run_epoch(state, xs_d, ys_d)
        losses.append(float(np.asarray(stats["loss"]).mean()))
    assert losses[-1] < losses[0] * 0.9, losses


def test_pp_sp_through_trainer_api():
    """DOWNPOUR(..., pipeline_stages=2, seq_shards=2) — the 3-axis
    long-context mesh through the reference-style trainer surface.
    The returned TrainedModel must be usable AS RETURNED: _finalize hands
    back the seq_axis=None twin for staged (dataclass) adapters too, so
    .predict works without a mesh — the reference contract is that
    ``trainer.train(df)`` returns a servable model, not one that traces
    ring-attention collectives outside any mesh."""
    import distkeras_tpu as dk

    x, y, onehot = toy_text(n=256)
    df = dk.from_numpy(x, onehot)
    model = _staged(True)
    t = dk.DOWNPOUR(model, loss="categorical_crossentropy",
                    worker_optimizer=("adam", {"learning_rate": 2e-3}),
                    num_workers=2, batch_size=16, num_epoch=10,
                    communication_window=2, pipeline_stages=2, seq_shards=2)
    trained = t.train(df)
    h = t.get_history()["loss"]
    assert h[-1] < h[0] * 0.8, h
    # the twin swap happened inside _finalize (same params, no seq axis) —
    # predict must run on a bare device, no manual dataclasses.replace
    assert trained.adapter.seq_axis is None
    probs = trained.predict(x)
    assert np.mean(np.argmax(np.asarray(probs), -1) == y) > 0.75


def test_pp_sp_rejections():
    with pytest.raises(ValueError, match="seq_axis"):
        # seq_shards without a ring-attention adapter
        _engine(_staged(False), seq_shards=2)
    with pytest.raises(ValueError, match="seq_axis"):
        # ring-attention adapter without its mesh axis
        _engine(_staged(True), seq_shards=1, devices=jax.devices()[:4])
    with pytest.raises(ValueError, match="not supported"):
        PipelineEngine(_staged(True), "categorical_crossentropy",
                       ("sgd", {"learning_rate": 0.05}), Downpour(2),
                       num_workers=1, tp_shards=2, seq_shards=2,
                       devices=jax.devices())
