"""Unit tests for the ops registries and epoch batching."""

import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distkeras_tpu.data import epoch_arrays, plan_epoch
from distkeras_tpu.ops import accuracy, get_loss, get_metric, get_optimizer


# -- losses ----------------------------------------------------------------

def test_categorical_crossentropy_logits_vs_probs():
    logits = jnp.asarray([[2.0, 0.0], [0.0, 2.0]])
    labels = jnp.asarray([[1.0, 0.0], [0.0, 1.0]])
    l_logits = get_loss("categorical_crossentropy", from_logits=True)(logits, labels)
    probs = jax.nn.softmax(logits) if (jax := __import__("jax")) else None
    l_probs = get_loss("categorical_crossentropy", from_logits=False)(probs, labels)
    np.testing.assert_allclose(float(l_logits), float(l_probs), rtol=1e-5)


def test_crossentropy_accepts_class_indices():
    logits = jnp.asarray([[5.0, 0.0], [0.0, 5.0]])
    l_idx = get_loss("categorical_crossentropy")(logits, jnp.asarray([0, 1]))
    l_oh = get_loss("categorical_crossentropy")(logits, jnp.eye(2))
    np.testing.assert_allclose(float(l_idx), float(l_oh), rtol=1e-6)


def test_mse_and_mae():
    p = jnp.asarray([[1.0], [3.0]])
    y = jnp.asarray([[0.0], [1.0]])
    assert float(get_loss("mse")(p, y)) == pytest.approx(2.5)
    assert float(get_loss("mae")(p, y)) == pytest.approx(1.5)


def test_binary_crossentropy_perfect_prediction_near_zero():
    p = jnp.asarray([[0.999], [0.001]])
    y = jnp.asarray([[1.0], [0.0]])
    assert float(get_loss("binary_crossentropy", from_logits=False)(p, y)) < 0.01


def test_unknown_loss_raises():
    with pytest.raises(ValueError):
        get_loss("nope")


# -- metrics ---------------------------------------------------------------

def test_accuracy_forms():
    preds = jnp.asarray([[0.9, 0.1], [0.2, 0.8]])
    assert float(accuracy(preds, jnp.asarray([0, 1]))) == 1.0
    assert float(accuracy(preds, jnp.eye(2))) == 1.0
    assert float(get_metric("accuracy")(preds, jnp.asarray([1, 1]))) == 0.5


# -- optimizers ------------------------------------------------------------

def test_optimizer_specs():
    assert isinstance(get_optimizer("sgd"), optax.GradientTransformation)
    assert isinstance(get_optimizer(("adam", {"learning_rate": 1e-2})), optax.GradientTransformation)
    tx = optax.sgd(0.1)
    assert get_optimizer(tx) is tx
    with pytest.raises(ValueError):
        get_optimizer("nadamax")


# -- epoch batching --------------------------------------------------------

def test_plan_epoch_covers_dataset():
    n_windows, total = plan_epoch(n=1000, num_workers=4, batch_size=32, window=5)
    assert total >= 1000
    assert total == n_windows * 5 * 4 * 32


def test_epoch_arrays_shapes_and_coverage():
    feats = np.arange(100, dtype=np.float32).reshape(100, 1)
    labels = np.arange(100, dtype=np.int32)
    xs, ys = epoch_arrays(feats, labels, num_workers=2, batch_size=8, window=3)
    assert xs.shape[0] == 2 and xs.shape[2] == 3 and xs.shape[3] == 8
    # wrap-padding: every original sample appears at least once
    assert set(ys.reshape(-1).tolist()) == set(range(100))


def test_epoch_arrays_stepwise_mode():
    feats = np.zeros((64, 4), np.float32)
    labels = np.zeros(64, np.int32)
    xs, ys = epoch_arrays(feats, labels, num_workers=4, batch_size=4, window=2,
                          stepwise=True)
    assert xs.ndim == 4  # [workers, steps, batch, features]
    assert xs.shape[0] == 4 and xs.shape[2] == 4


def test_epoch_arrays_shuffle_determinism():
    feats = np.arange(50, dtype=np.float32).reshape(50, 1)
    labels = np.arange(50, dtype=np.int32)
    a = epoch_arrays(feats, labels, 2, 5, 2, rng=np.random.default_rng(3))[1]
    b = epoch_arrays(feats, labels, 2, 5, 2, rng=np.random.default_rng(3))[1]
    c = epoch_arrays(feats, labels, 2, 5, 2, rng=np.random.default_rng(4))[1]
    np.testing.assert_array_equal(a, b)
    assert not np.array_equal(a, c)


def test_epoch_arrays_empty_raises():
    with pytest.raises(ValueError):
        epoch_arrays(np.zeros((0, 3)), np.zeros(0), 2, 4, 2)
