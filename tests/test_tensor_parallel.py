"""Tensor parallelism (GSPMD engine): param leaves sharded over a 'model'
mesh axis, collectives inserted by the XLA partitioner.

The reference has no TP (SURVEY.md §2 census: "out of reference scope;
optional stretch via pjit param sharding") — these tests pin down that the
stretch implementation changes *where arrays live*, never *what is computed*:
the TP training trajectory must match the plain data-parallel one."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import distkeras_tpu as dk
from distkeras_tpu.algorithms import Adag, Downpour
from distkeras_tpu.frame import from_numpy
from distkeras_tpu.models import MLP, FlaxModel, TransformerClassifier
from distkeras_tpu.parallel import TP_AXIS, GSPMDEngine, WindowedEngine


def _data(n=256, d=16, classes=4, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = np.argmax(x @ rng.normal(size=(d, classes)), axis=1).astype(np.int32)
    return x, y, np.eye(classes, dtype=np.float32)[y]


def _epoch_arrays(x, onehot, num_workers, n_windows, window, batch):
    n = num_workers * n_windows * window * batch
    xs = x[:n].reshape(num_workers, n_windows, window, batch, -1)
    ys = np.argmax(onehot[:n], -1).reshape(num_workers, n_windows, window, batch)
    return xs, ys.astype(np.int32)


def _run(engine, xs_np, ys_np, x0, epochs=2):
    state = engine.init_state(jax.random.PRNGKey(0), x0)
    xs, ys = engine.shard_batches(xs_np, ys_np)
    for _ in range(epochs):
        state, stats = engine.run_epoch(state, xs, ys)
    return (jax.tree.map(np.asarray, state.center_params),
            np.asarray(stats["loss"]))


def test_tp_matches_dp_trajectory():
    """4 workers x 2 model shards computes the same training run as
    4 workers unsharded — TP is a layout, not an algorithm."""
    x, y, onehot = _data()
    adapter = lambda: FlaxModel(MLP(features=(32, 16), num_classes=4))
    xs, ys = _epoch_arrays(x, onehot, num_workers=4, n_windows=2, window=4, batch=8)

    dp = WindowedEngine(adapter(), "categorical_crossentropy",
                        ("sgd", {"learning_rate": 0.05}), Downpour(4),
                        num_workers=4, metrics=())
    tp = GSPMDEngine(adapter(), "categorical_crossentropy",
                     ("sgd", {"learning_rate": 0.05}), Downpour(4),
                     num_workers=4, tp_shards=2, metrics=())
    p_dp, loss_dp = _run(dp, xs, ys, x[:8])
    p_tp, loss_tp = _run(tp, xs, ys, x[:8])

    flat_dp, flat_tp = jax.tree.leaves(p_dp), jax.tree.leaves(p_tp)
    assert len(flat_dp) == len(flat_tp)
    for a, b in zip(flat_dp, flat_tp):
        np.testing.assert_allclose(a, b, rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(loss_dp, loss_tp, rtol=2e-5, atol=2e-6)


def test_tp_param_leaves_are_model_sharded():
    x, _, onehot = _data()
    engine = GSPMDEngine(FlaxModel(MLP(features=(32, 16), num_classes=4)),
                         "categorical_crossentropy", "sgd", Downpour(4),
                         num_workers=4, tp_shards=2, metrics=())
    state = engine.init_state(jax.random.PRNGKey(0), x[:8])
    specs = [
        (leaf.shape, leaf.sharding.spec)
        for leaf in jax.tree.leaves(state.center_params)
    ]
    tp_sharded = [s for shape, s in specs if TP_AXIS in jax.tree.leaves(tuple(s))]
    # every 2-D kernel with an even last dim must land on the model axis
    kernels = [shape for shape, _ in specs if len(shape) >= 2 and shape[-1] % 2 == 0]
    assert len(tp_sharded) == len(kernels) and kernels, specs
    # and per-worker state carries workers + model axes
    local_specs = {
        str(leaf.sharding.spec)
        for leaf in jax.tree.leaves(state.local_params)
    }
    assert any(TP_AXIS in s for s in local_specs), local_specs


def test_tp_virtual_workers():
    """num_workers may exceed the worker mesh axis (8 logical on a 4x2 mesh)."""
    x, y, onehot = _data(n=512)
    xs, ys = _epoch_arrays(x, onehot, num_workers=8, n_windows=1, window=4, batch=8)
    engine = GSPMDEngine(FlaxModel(MLP(features=(32,), num_classes=4)),
                         "categorical_crossentropy", "sgd", Downpour(4),
                         num_workers=8, tp_shards=2, metrics=())
    params, loss = _run(engine, xs, ys, x[:8], epochs=1)
    assert np.isfinite(loss).all()


def test_trainer_level_tp_converges(toy_classification):
    x, y, onehot = toy_classification
    df = from_numpy(x, onehot)
    t = dk.DOWNPOUR(FlaxModel(MLP(features=(32,), num_classes=2)),
                    loss="categorical_crossentropy",
                    worker_optimizer=("sgd", {"learning_rate": 0.1}),
                    num_workers=4, batch_size=16, num_epoch=8,
                    communication_window=4, tp_shards=2)
    trained = t.train(df)
    h = t.get_history()["loss"]
    assert h[-1] < h[0] * 0.6
    preds = np.argmax(trained.predict(x), -1)
    assert np.mean(preds == np.argmax(onehot, -1)) > 0.8


def test_tp_transformer_adag():
    """TP engine is model-agnostic: the (unmodified, seq_axis=None)
    Transformer trains under ADAG on a (2 workers x 2 model) mesh."""
    rng = np.random.default_rng(0)
    x = rng.integers(0, 50, size=(128, 16)).astype(np.int32)
    y = ((x == 7).sum(1) > (x == 3).sum(1)).astype(np.int32)
    xs = x.reshape(2, 2, 4, 8, 16)
    ys = y.reshape(2, 2, 4, 8).astype(np.int32)
    engine = GSPMDEngine(
        FlaxModel(TransformerClassifier(vocab_size=50, num_classes=2, dim=16,
                                        heads=2, num_layers=1, max_len=16)),
        "categorical_crossentropy", ("adam", {"learning_rate": 1e-3}),
        Adag(4), num_workers=2, tp_shards=2, metrics=(),
    )
    params, loss = _run(engine, xs, ys, x[:8], epochs=1)
    assert np.isfinite(loss).all()


def test_tp_rejects_bad_combos():
    with pytest.raises(ValueError):
        # tp_shards must divide the device count (8 CPU devices in tests)
        GSPMDEngine(FlaxModel(MLP()), "categorical_crossentropy", "sgd",
                    Downpour(4), num_workers=4, tp_shards=3)
    with pytest.raises(ValueError):
        dk.DOWNPOUR(FlaxModel(MLP()), num_workers=4, tp_shards=2,
                    seq_shards=2).train(from_numpy(*_data()[::2]))


def test_tp_checkpoint_resume(toy_classification, tmp_path):
    """TP-sharded training state round-trips through Orbax: 4 epochs straight
    == 2 epochs + resume 2 (same seed, same data order)."""
    x, y, onehot = toy_classification
    df = from_numpy(x, onehot)

    def make(num_epoch, resume=False, ckpt=None):
        return dk.DOWNPOUR(FlaxModel(MLP(features=(16,), num_classes=2)),
                           loss="categorical_crossentropy",
                           worker_optimizer=("sgd", {"learning_rate": 0.05}),
                           num_workers=4, batch_size=16, num_epoch=num_epoch,
                           communication_window=4, seed=11, tp_shards=2,
                           checkpoint_dir=ckpt, checkpoint_every=1,
                           resume=resume)

    straight = make(4).train(df)
    make(2, ckpt=str(tmp_path)).train(df)
    resumed = make(4, resume=True, ckpt=str(tmp_path)).train(df)
    for a, b in zip(jax.tree.leaves(straight.params), jax.tree.leaves(resumed.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)


def test_tp_with_keras_model():
    """The GSPMD engine is adapter-agnostic: a Keras-3 (JAX backend) model
    trains with tp_shards=2 and returns a Keras model."""
    keras = pytest.importorskip("keras")
    from keras import layers

    rng = np.random.default_rng(0)
    x = rng.normal(size=(256, 8)).astype(np.float32)
    y = (x @ rng.normal(size=(8,)) > 0).astype(np.int32)
    df = from_numpy(x, np.eye(2, dtype=np.float32)[y])

    model = keras.Sequential([
        keras.Input((8,)),
        layers.Dense(16, activation="relu"),
        layers.Dense(2, activation="softmax"),
    ])
    t = dk.DOWNPOUR(model, loss="categorical_crossentropy",
                    worker_optimizer=("sgd", {"learning_rate": 0.1}),
                    num_workers=4, batch_size=16, num_epoch=4,
                    communication_window=4, tp_shards=2)
    trained = t.train(df)
    preds = np.argmax(trained.predict(x, verbose=0), -1)
    assert np.mean(preds == y) > 0.75


def test_tp_staleness_schedule_matches_shard_map_engine():
    """commit_schedule (deterministic asynchrony) under TP reproduces the
    shard_map engine's stepwise trajectory exactly."""
    from distkeras_tpu.algorithms import DynSGD

    x, y, onehot = _data(n=512)
    num_workers, n_steps, batch = 4, 8, 8
    n = num_workers * n_steps * batch
    xs = x[:n].reshape(num_workers, n_steps, batch, -1)
    ys = np.argmax(onehot[:n], -1).reshape(num_workers, n_steps, batch).astype(np.int32)
    schedule = [2, 3, 4, 5]

    ref = WindowedEngine(FlaxModel(MLP(features=(32,), num_classes=4)),
                         "categorical_crossentropy", ("sgd", {"learning_rate": 0.05}),
                         DynSGD(4), num_workers=num_workers, metrics=(),
                         commit_schedule=schedule)
    tp = GSPMDEngine(FlaxModel(MLP(features=(32,), num_classes=4)),
                     "categorical_crossentropy", ("sgd", {"learning_rate": 0.05}),
                     DynSGD(4), num_workers=num_workers, tp_shards=2, metrics=(),
                     commit_schedule=schedule)
    p_ref, loss_ref = _run(ref, xs, ys, x[:8], epochs=1)
    p_tp, loss_tp = _run(tp, xs, ys, x[:8], epochs=1)
    for a, b in zip(jax.tree.leaves(p_ref), jax.tree.leaves(p_tp)):
        np.testing.assert_allclose(a, b, rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(loss_ref, loss_tp, rtol=2e-5, atol=2e-6)
