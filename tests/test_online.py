"""Online-loop tests: capture admission (deterministic sampling, content
filter, per-tenant window quotas), atomic window publication readable back
through ``MemmapSource``, the journal/sidecar crash-resume protocol — a
capture killed between shard rotation and manifest publish must resume
**bitwise**, losing and duplicating nothing (the satellite-3 property) —
the ``WindowScheduler``'s window→verified-checkpoint pipeline with chaos
retries, capacity-aware trainer/replica placement, the daemon's
``online_loop``/``online_status``/``stop_online`` verbs, the frontend
capture hook, and the ``online_*`` metric schema pinned as golden
Prometheus text."""

import hashlib
import json
import os

import numpy as np
import pytest

from distkeras_tpu import chaos, telemetry
from distkeras_tpu.datapipe.source import atomic_write_npy
from distkeras_tpu.datapipe.state import DataState
from distkeras_tpu.job_deployment import Job, PunchcardServer
from distkeras_tpu.online import (
    SamplingPolicy,
    TrafficLog,
    WindowScheduler,
    load_window_manifest,
    online_metrics,
    plan_placement,
    published_windows,
    verify_window,
    window_source,
)
from distkeras_tpu.serving import GenerateRequest, GenerateResult
from distkeras_tpu.telemetry.metrics import Registry

GOLDEN = os.path.join(os.path.dirname(os.path.abspath(__file__)), "golden")


@pytest.fixture(autouse=True)
def clean_online():
    chaos.configure("")  # chaos off, counters clear, for every test
    yield
    chaos.configure(None)
    telemetry.configure(None)


def _gen(i, tenant=""):
    """One deterministic served generation (request, result) pair."""
    req = GenerateRequest(prompt=[1 + i, 2, 3 + (i % 4)], tenant=tenant)
    res = GenerateResult(request_id=f"r{i}", prompt=req.prompt,
                         tokens=[5, 6 + (i % 3)], finish_reason="length")
    return req, res


def _capture_digest(directory):
    """sha256 of every published artifact (shards, manifests, sidecar) —
    journals excluded: they are working state, not publication."""
    out = {}
    for name in sorted(os.listdir(directory)):
        if name.startswith("journal_"):
            continue
        with open(os.path.join(directory, name), "rb") as fh:
            out[name] = hashlib.sha256(fh.read()).hexdigest()
    return out


# ------------------------------------------------------------ metric schema


def test_online_metrics_schema_golden():
    registry = Registry()
    m = online_metrics(registry)
    m["ingested"].inc(5)
    m["dropped"].inc(3)
    m["quota_drops"].inc(2)
    m["rate_drops"].inc(4)
    m["capture_errors"].inc(1)
    m["windows_published"].inc(2)
    m["windows_trained"].inc(2)
    m["retrain_failures"].inc(1)
    m["window_lag_seconds"].set(1.5)
    m["swap_age_seconds"].set(2.5)
    m["retrain_seconds"].observe(0.5)
    golden = open(os.path.join(GOLDEN, "online_metrics.txt")).read()
    assert registry.to_prometheus(labels={"run_id": "fleet1234"}) == golden
    # get-or-create: a second call must hand back the same instruments
    assert online_metrics(registry)["ingested"] is m["ingested"]


# -------------------------------------------------------- sampling policy


def test_sampling_policy_deterministic_across_instances():
    a = SamplingPolicy(rate=0.5, seed=11)
    b = SamplingPolicy(rate=0.5, seed=11)
    decisions = [a._keep(seq) for seq in range(200)]
    assert decisions == [b._keep(seq) for seq in range(200)]
    kept = sum(decisions)
    assert 0 < kept < 200  # actually samples, both ways
    # a different seed draws a different subset
    c = SamplingPolicy(rate=0.5, seed=12)
    assert decisions != [c._keep(seq) for seq in range(200)]


def test_sampling_policy_admission_reasons():
    policy = SamplingPolicy(tenant_quota=2,
                            filter=lambda prompt, tokens: len(tokens) > 1)
    assert policy.admit(0, "t", 0, [1], [2, 3]) is None
    assert policy.admit(1, "t", 2, [1], [2, 3]) == "quota"
    assert policy.admit(2, "t", 0, [1], [2]) == "filtered"
    assert SamplingPolicy(rate=0.0).admit(3, "t", 0, [1], [2]) == "sampled"


def test_sampling_policy_validation():
    with pytest.raises(ValueError):
        SamplingPolicy(rate=1.5)
    with pytest.raises(ValueError):
        SamplingPolicy(tenant_quota=0)
    with pytest.raises(ValueError):
        SamplingPolicy(tenant_rate=0.0)
    with pytest.raises(ValueError):
        SamplingPolicy(rate_unit="bogus")


class _FixedRateLedger:
    """Stand-in for the accounting ledger: fixed rolling rates by tenant."""

    def __init__(self, rates, unit="tokens"):
        self.rates, self.unit = rates, unit

    def rolling_rate(self, tenant, unit="tokens"):
        assert unit == self.unit
        return self.rates.get(tenant, 0.0)


def test_sampling_policy_tenant_rate_thins_hot_tenant():
    ledger = _FixedRateLedger({"hot": 40.0, "warm": 10.0}, unit="tokens")
    policy = SamplingPolicy(tenant_rate=10.0, rate_unit="tokens",
                            ledger=ledger, seed=7)
    # at or under the target: never thinned
    assert all(policy.admit(s, "warm", 0, [1], [2]) is None
               for s in range(200))
    # unknown to the ledger (rate 0.0): no usage signal, no throttle
    assert all(policy.admit(s, "cold", 0, [1], [2]) is None
               for s in range(50))
    # 4x over target: thinned to ~target/observed = 25% admitted
    decisions = [policy.admit(s, "hot", 0, [1], [2]) for s in range(400)]
    drops = decisions.count("rate")
    assert 0 < 400 - drops < 400
    assert abs((400 - drops) / 400 - 0.25) < 0.1
    # stateless determinism: a fresh instance re-derives every decision
    again = SamplingPolicy(tenant_rate=10.0, rate_unit="tokens",
                           ledger=ledger, seed=7)
    assert decisions == [again.admit(s, "hot", 0, [1], [2])
                         for s in range(400)]
    # the rate draw is decorrelated from the sampling draw: with rate=1.0
    # the two gates can't shadow each other's subsets
    mixed = SamplingPolicy(rate=0.5, tenant_rate=10.0, rate_unit="tokens",
                           ledger=ledger, seed=7)
    reasons = {mixed.admit(s, "hot", 0, [1], [2]) for s in range(200)}
    assert reasons == {None, "sampled", "rate"}
    # without a ledger the knob is inert
    assert SamplingPolicy(tenant_rate=10.0).admit(0, "hot", 0, [1], [2]) \
        is None


def test_sampling_policy_rate_unit_requests():
    ledger = _FixedRateLedger({"hot": 8.0}, unit="requests")
    policy = SamplingPolicy(tenant_rate=2.0, rate_unit="samples",
                            ledger=ledger, seed=3)
    decisions = [policy.admit(s, "hot", 0, [1], [2]) for s in range(400)]
    admitted = decisions.count(None)
    assert abs(admitted / 400 - 0.25) < 0.1  # 2/8 of traffic admitted


# -------------------------------------------------- capture + publication


def test_capture_rotates_into_memmap_windows(tmp_path):
    d = str(tmp_path / "cap")
    registry = Registry()
    log = TrafficLog(d, window_samples=4, max_len=8, registry=registry)
    for i in range(9):
        req, res = _gen(i, tenant="t")
        assert log.record(req, res) is True
    assert published_windows(d) == [0, 1]
    assert log.pending == 1  # the ninth sample waits for the next window
    manifest = load_window_manifest(d, 1)
    assert manifest["samples"] == 4
    assert manifest["first_seq"] == 4 and manifest["last_seq"] == 7
    assert manifest["tenants"] == {"t": 4}
    assert verify_window(d, 0) is None and verify_window(d, 1) is None
    source = window_source(d, 0)
    feats, lens = source.local_arrays()
    assert feats.shape == (4, 8) and feats.dtype == np.int32
    req0, res0 = _gen(0, tenant="t")
    merged = [int(t) for t in req0.prompt + res0.tokens]
    assert feats[0, :len(merged)].tolist() == merged
    assert int(lens[0]) == len(merged)
    snap = registry.snapshot()
    assert snap["online_samples_ingested_total"]["value"] == 9
    assert snap["online_windows_published_total"]["value"] == 2
    log.close()


def test_capture_tenant_quota_caps_hot_tenant(tmp_path):
    d = str(tmp_path / "cap")
    registry = Registry()
    log = TrafficLog(d, window_samples=4, max_len=8,
                     policy=SamplingPolicy(tenant_quota=2), registry=registry)
    # 75% hot traffic: the quota must cap hot at 2 per window while the
    # cold tenant still gets through and windows keep rotating
    admitted = [log.record(*_gen(i, tenant="hot" if i % 4 < 3 else "cold"))
                for i in range(16)]
    assert published_windows(d) == [0, 1]
    for w in published_windows(d):
        tenants = load_window_manifest(d, w)["tenants"]
        assert tenants["hot"] <= 2
        assert tenants["cold"] >= 1
    drops = admitted.count(False)
    assert drops > 0
    snap = registry.snapshot()
    assert snap["online_quota_drops_total"]["value"] == drops
    assert snap["online_samples_dropped_total"]["value"] == drops
    assert log.dropped()["quota"] == drops
    log.close()


def test_capture_tenant_rate_policy_counts_rate_drops(tmp_path):
    d = str(tmp_path / "cap")
    registry = Registry()
    ledger = _FixedRateLedger({"hot": 100.0}, unit="tokens")
    log = TrafficLog(d, window_samples=4, max_len=8,
                     policy=SamplingPolicy(tenant_rate=25.0,
                                           rate_unit="tokens",
                                           ledger=ledger, seed=9),
                     registry=registry)
    admitted = [log.record(*_gen(i, tenant="hot")) for i in range(40)]
    drops = admitted.count(False)
    assert 0 < drops < 40  # thinned toward 25%, not zeroed
    snap = registry.snapshot()
    assert snap["online_rate_drops_total"]["value"] == drops
    assert snap["online_samples_dropped_total"]["value"] == drops
    assert log.dropped()["rate"] == drops
    log.close()


def test_capture_flush_publishes_partial_window(tmp_path):
    d = str(tmp_path / "cap")
    log = TrafficLog(d, window_samples=64, max_len=8)
    for i in range(3):
        log.record(*_gen(i))
    assert log.flush() == 0
    assert load_window_manifest(d, 0)["samples"] == 3
    assert log.flush() is None  # nothing pending
    log.close()


def test_verify_window_catches_torn_shard(tmp_path):
    d = str(tmp_path / "cap")
    log = TrafficLog(d, window_samples=2, max_len=8)
    for i in range(2):
        log.record(*_gen(i))
    log.close()
    shard = os.path.join(d, "window_000000.features.npy")
    with open(shard, "r+b") as fh:
        fh.truncate(os.path.getsize(shard) - 8)
    assert "bytes" in verify_window(d, 0)


def test_atomic_write_npy_roundtrip_and_no_tmp_left(tmp_path):
    path = str(tmp_path / "a.npy")
    arr = np.arange(12, dtype=np.int32).reshape(3, 4)
    atomic_write_npy(path, arr)
    assert (np.load(path) == arr).all()
    assert not os.path.exists(path + ".tmp")


# ------------------------------------------------------------ crash resume


def test_capture_plain_restart_resumes_cursor(tmp_path):
    d = str(tmp_path / "cap")
    log = TrafficLog(d, window_samples=4, max_len=8)
    for i in range(6):
        log.record(*_gen(i, tenant="t"))
    log.close()
    resumed = TrafficLog(d, window_samples=4, max_len=8)
    assert resumed.next_seq == 6
    assert resumed.window == 1
    assert resumed.pending == 2  # the two carry-over rows survived
    for i in range(6, 8):
        resumed.record(*_gen(i, tenant="t"))
    assert published_windows(d) == [0, 1]
    resumed.close()


def test_capture_resume_after_kill_between_rotate_and_manifest(tmp_path):
    """The satellite-3 property: a seeded kill BETWEEN shard rotation and
    manifest publish (chaos ``window_rotate`` site), then resume — the
    interrupted publication completes idempotently and every subsequent
    byte matches an uninterrupted reference capture: no sample lost, none
    duplicated, DataState sidecar included."""
    kwargs = dict(window_samples=4, max_len=8)
    policy = lambda: SamplingPolicy(tenant_quota=3, seed=5)

    ref_dir = str(tmp_path / "ref")
    ref = TrafficLog(ref_dir, policy=policy(), **kwargs)
    for i in range(14):
        ref.record(*_gen(i, tenant=f"t{i % 2}"))
    ref.close()

    kill_dir = str(tmp_path / "kill")
    chaos.configure("23:kill_rotate=2")
    log = TrafficLog(kill_dir, policy=policy(), **kwargs)
    killed = 0
    for i in range(14):
        req, res = _gen(i, tenant=f"t{i % 2}")
        try:
            log.record(req, res)
        except chaos.ChaosKilled:
            # the offered sample was journaled before the kill: the resumed
            # log owns it — re-offering here would be the duplication bug
            killed += 1
            chaos.configure("")
            log = TrafficLog(kill_dir, policy=policy(), **kwargs)
    log.close()
    assert killed == 1, "the seeded mid-rotation kill must fire"

    assert _capture_digest(kill_dir) == _capture_digest(ref_dir)
    # no loss, no duplication: published windows own contiguous,
    # non-overlapping seq ranges that exactly tile the admitted stream
    windows = published_windows(kill_dir)
    assert windows == published_windows(ref_dir) == [0, 1, 2]
    next_seq = 0
    for w in windows:
        m = load_window_manifest(kill_dir, w)
        assert m["first_seq"] == next_seq
        assert m["samples"] == m["last_seq"] - m["first_seq"] + 1 == 4
        feats, _ = window_source(kill_dir, w).local_arrays()
        assert len(feats) == 4
        next_seq = m["last_seq"] + 1
    with open(os.path.join(kill_dir, "capture_state.json")) as fh:
        state = json.load(fh)
    assert DataState.from_json(state["data_state"]).block_cursor == 14


def test_capture_resume_completes_interrupted_rotation_only_once(tmp_path):
    d = str(tmp_path / "cap")
    chaos.configure("7:kill_rotate=0")
    log = TrafficLog(d, window_samples=3, max_len=8)
    with pytest.raises(chaos.ChaosKilled):
        for i in range(3):
            log.record(*_gen(i))
    chaos.configure("")
    assert published_windows(d) == []  # shards landed, manifest did not
    resumed = TrafficLog(d, window_samples=3, max_len=8)
    assert published_windows(d) == [0]  # completed on resume
    assert resumed.pending == 0 and resumed.window == 1
    assert verify_window(d, 0) is None
    # resuming again is a no-op, not a re-publication
    resumed.close()
    again = TrafficLog(d, window_samples=3, max_len=8)
    assert published_windows(d) == [0] and again.next_seq == 3
    again.close()


# -------------------------------------------------------- window scheduler


def _np_train_fn(calls):
    def train_fn(window, source):
        feats, lens = source.local_arrays()
        calls.append((window, len(feats)))
        return {"w": np.full((2, 2), float(window + 1), np.float32),
                "rows": np.asarray([len(feats)], np.int32)}
    return train_fn


def test_window_scheduler_trains_published_windows(tmp_path):
    cap = str(tmp_path / "cap")
    ckpt = str(tmp_path / "ckpt")
    log = TrafficLog(cap, window_samples=3, max_len=8)
    for i in range(6):
        log.record(*_gen(i))
    log.close()
    calls = []
    registry = Registry()
    sched = WindowScheduler(cap, _np_train_fn(calls), ckpt,
                            registry=registry)
    assert sched.pending_windows() == [0, 1]
    assert sched.step_once() == 0
    assert sched.step_once() == 1
    assert sched.step_once() is None
    assert calls == [(0, 3), (1, 3)]
    from distkeras_tpu.checkpoint import (
        committed_steps,
        restore_checkpoint,
        restore_data_state,
    )

    assert committed_steps(ckpt) == [1, 2]
    state = restore_checkpoint(ckpt, step=2, verify="full")
    assert float(np.asarray(state["w"])[0, 0]) == 2.0
    ds = restore_data_state(ckpt, step=2)
    assert ds.epoch == 1
    assert ds.block_cursor == load_window_manifest(cap, 1)["last_seq"] + 1
    snap = registry.snapshot()
    assert snap["online_windows_trained_total"]["value"] == 2
    assert snap["online_retrain_seconds"]["count"] == 2
    # restart safety: a new scheduler baselines on committed steps and
    # never re-trains a closed window
    calls2 = []
    sched2 = WindowScheduler(cap, _np_train_fn(calls2), ckpt)
    assert sched2.trained == 1
    assert sched2.step_once() is None and calls2 == []


def test_window_scheduler_retries_chaos_killed_epoch(tmp_path):
    cap = str(tmp_path / "cap")
    log = TrafficLog(cap, window_samples=2, max_len=8)
    for i in range(2):
        log.record(*_gen(i))
    log.close()
    calls = []
    registry = Registry()
    chaos.configure("3:kill_epoch=0")
    sched = WindowScheduler(cap, _np_train_fn(calls), str(tmp_path / "ckpt"),
                            registry=registry)
    assert sched.step_once() == 0  # first attempt killed, retry trains
    assert calls == [(0, 2)]
    snap = registry.snapshot()
    assert snap["online_retrain_failures_total"]["value"] == 1


def test_window_scheduler_refuses_torn_window(tmp_path):
    cap = str(tmp_path / "cap")
    log = TrafficLog(cap, window_samples=2, max_len=8)
    for i in range(2):
        log.record(*_gen(i))
    log.close()
    shard = os.path.join(cap, "window_000000.labels.npy")
    with open(shard, "r+b") as fh:
        fh.truncate(os.path.getsize(shard) - 4)
    sched = WindowScheduler(cap, _np_train_fn([]), str(tmp_path / "ckpt"))
    with pytest.raises(RuntimeError, match="shard verification"):
        sched.step_once()


def test_window_scheduler_background_loop(tmp_path):
    import time as _time

    cap = str(tmp_path / "cap")
    log = TrafficLog(cap, window_samples=2, max_len=8)
    calls = []
    sched = WindowScheduler(cap, _np_train_fn(calls), str(tmp_path / "ckpt"),
                            poll_interval=0.02)
    sched.start()
    try:
        for i in range(4):
            log.record(*_gen(i))
        deadline = _time.monotonic() + 10
        while len(calls) < 2 and _time.monotonic() < deadline:
            _time.sleep(0.02)
    finally:
        sched.stop()
        log.close()
    assert [w for w, _ in calls] == [0, 1]
    assert sched.status()["windows_trained"] == 2
    assert sched.status()["pending"] == []


# --------------------------------------------------------------- placement


def test_plan_placement_trainer_on_largest_member():
    members = {"a": {"workers": 2}, "b": {"workers": 8}, "c": {"workers": 4}}
    plan = plan_placement(members, replicas=3)
    assert plan["trainer"] == "b"
    assert sum(plan["replicas"].values()) == 3
    assert "b" not in plan["replicas"]  # enough capacity without the trainer
    assert plan["capacity"] == 14


def test_plan_placement_small_fleet_shares_trainer():
    plan = plan_placement({"only": {"workers": 2}}, replicas=2)
    assert plan["trainer"] == "only"
    assert plan["replicas"] == {"only": 2}
    overflow = plan_placement({"big": {"workers": 4}, "tiny": {"workers": 1}},
                              replicas=3)
    assert overflow["trainer"] == "big"
    assert overflow["replicas"]["tiny"] >= 1
    assert sum(overflow["replicas"].values()) == 3


def test_plan_placement_empty_fleet():
    assert plan_placement({}, replicas=2) == {
        "trainer": None, "replicas": {}, "capacity": 0}


# ------------------------------------------------------------ daemon verbs


@pytest.fixture
def punchcard(tmp_path):
    workdir = tmp_path / "punchcard"
    workdir.mkdir()
    server = PunchcardServer(port=0, secret="s3cret", workdir=str(workdir))
    server.start()
    yield server
    server.stop()


SLEEPER = "import time\ntime.sleep(60)\n"


def test_daemon_online_loop_status_stop(punchcard):
    job = Job("127.0.0.1", punchcard.port, secret="s3cret", script=SLEEPER)
    job._rpc({"action": "register", "worker_id": "w-big", "workers": 4})
    job._rpc({"action": "register", "worker_id": "w-small", "workers": 1})
    online_id = job.online_loop(replicas=2, trainer_script=SLEEPER)
    assert job.online_id == online_id and job.tier_id
    st = job.online_status()
    assert st["status"] == "ok"
    assert len(st["replicas"]) == 2 and st["serving"] == 2
    assert st["trainer"]["status"] == "serving"
    assert st["windows_published"] == 0 and st["steps_published"] == 0
    assert st["placement"]["trainer"] == "w-big"
    assert os.path.isdir(st["capture_dir"])
    assert os.path.isdir(st["checkpoint_dir"])
    stopped = job.stop_online()
    assert stopped["status"] == "stopped" and stopped["stopped"] == 3
    assert job.online_status(online_id)["status"] == "unknown"
    assert job.tier_status()["status"] == "unknown"  # tier went with it


def test_daemon_online_status_counts_windows_and_steps(punchcard, tmp_path):
    cap = str(tmp_path / "cap")
    ckpt = str(tmp_path / "ckpt")
    job = Job("127.0.0.1", punchcard.port, secret="s3cret", script=SLEEPER)
    job.online_loop(replicas=1, trainer_script=SLEEPER,
                    capture_dir=cap, checkpoint_dir=ckpt)
    log = TrafficLog(cap, window_samples=2, max_len=8)
    for i in range(4):
        log.record(*_gen(i))
    log.close()
    WindowScheduler(cap, _np_train_fn([]), ckpt).step_once()
    st = job.online_status()
    assert st["windows_published"] == 2
    assert st["steps_published"] == 1
    job.stop_online()


def test_daemon_online_unknown_ids(punchcard):
    job = Job("127.0.0.1", punchcard.port, secret="s3cret", script=SLEEPER)
    assert job.online_status("nope")["status"] == "unknown"
    assert job.stop_online("nope")["status"] == "unknown"
    with pytest.raises(RuntimeError):
        job.online_status()


# ---------------------------------------------------- frontend capture hook


class _FakePending:
    def __init__(self, result):
        self._result = result

    def result(self, timeout=None):
        return self._result


class _FakeEngine:
    def __init__(self, result):
        self._result = result
        self.submitted = []

    def submit(self, req):
        self.submitted.append(req)
        return _FakePending(self._result)


def _install(engine, traffic_log, monkeypatch):
    from distkeras_tpu.serving import frontend
    from distkeras_tpu.telemetry.flightdeck import server as server_mod

    handlers = {}
    monkeypatch.setattr(server_mod, "add_endpoint",
                        lambda path, fn: handlers.update({path: fn}))
    frontend.install_http_endpoint(engine, traffic_log=traffic_log)
    return handlers["/generate"]


def test_frontend_records_successful_generation(monkeypatch):
    result = GenerateResult(request_id="r", prompt=[1, 2], tokens=[3],
                            finish_reason="length")
    engine = _FakeEngine(result)
    recorded = []

    class _Log:
        def record(self, req, res):
            recorded.append((req, res))
            return True

    handle = _install(engine, _Log(), monkeypatch)
    body = json.dumps({"prompt": [1, 2], "tenant": "acme"})
    _, _, status = handle({"method": "POST", "body": body})[:3]
    assert status == 200
    assert len(recorded) == 1
    assert recorded[0][0].tenant == "acme"
    assert recorded[0][1] is result


def test_frontend_tenant_header_fallback(monkeypatch):
    engine = _FakeEngine(GenerateResult(request_id="r", prompt=[1],
                                        tokens=[2], finish_reason="length"))
    recorded = []

    class _Log:
        def record(self, req, res):
            recorded.append(req)

    handle = _install(engine, _Log(), monkeypatch)
    handle({"method": "POST", "body": json.dumps({"prompt": [1]}),
            "headers": {"x-dk-tenant": "hdr-tenant"}})
    assert recorded[0].tenant == "hdr-tenant"


def test_frontend_capture_failure_never_breaks_serving(monkeypatch):
    engine = _FakeEngine(GenerateResult(request_id="r", prompt=[1],
                                        tokens=[2], finish_reason="length"))

    class _ExplodingLog:
        def record(self, req, res):
            raise RuntimeError("capture disk full")

    handle = _install(engine, _ExplodingLog(), monkeypatch)
    _, body, status = handle(
        {"method": "POST", "body": json.dumps({"prompt": [1]})})[:3]
    assert status == 200  # the client never sees the capture fault
    assert json.loads(body)["tokens"] == [2]


def test_frontend_no_capture_on_aborted(monkeypatch):
    engine = _FakeEngine(GenerateResult(request_id="r", prompt=[1], tokens=[],
                                        finish_reason="aborted"))
    recorded = []

    class _Log:
        def record(self, req, res):
            recorded.append(req)

    handle = _install(engine, _Log(), monkeypatch)
    out = handle({"method": "POST", "body": json.dumps({"prompt": [1]})})
    assert out[2] == 503
    assert recorded == []  # failed generations are not training data
