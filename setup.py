from setuptools import find_packages, setup

setup(
    name="distkeras-tpu",
    version="0.1.0",
    description=(
        "TPU-native distributed deep learning: the dist-keras trainer/"
        "transformer/predictor API on JAX/XLA meshes instead of Spark"
    ),
    long_description=open("README.md", encoding="utf-8").read(),
    long_description_content_type="text/markdown",
    license="MIT",
    packages=find_packages(include=["distkeras_tpu", "distkeras_tpu.*"]),
    python_requires=">=3.10",
    install_requires=[
        "jax",
        "flax",
        "optax",
        "numpy",
    ],
    extras_require={
        "keras": ["keras>=3.0"],
        "checkpoint": ["orbax-checkpoint"],
        "test": ["pytest", "chex"],
    },
    classifiers=[
        "Programming Language :: Python :: 3",
        "Topic :: Scientific/Engineering :: Artificial Intelligence",
    ],
)
