"""Accuracy proof on the benchmark models — the "matched final accuracy"
evidence BASELINE.json's north star demands (VERDICT r2 item 4, hardened
per VERDICT r3 item 1).

Trains ALL SIX trainer families (SingleTrainer + the five async
algorithms) on the CIFAR-10-CNN-shaped and IMDB-TextCNN-shaped tasks end
to end through the DataFrame pipeline, printing one JSON line per
(dataset, trainer) with each async trainer's accuracy gap to SingleTrainer
on the same data — the benchmark-scale analogue of the README's digits
experiment table.

Datasets: real CIFAR-10 / IMDB when a local cache exists (keras.datasets;
this environment has no network), otherwise **deterministic learnable
proxies** of the same shape/scale, deliberately hardened so SingleTrainer
lands ~0.85-0.93 instead of saturating (a saturated task cannot detect an
async-accuracy regression — round 3's artifact read 1.0 / 0.997):

* ``cifar_proxy`` — 32x32x3 oriented sinusoidal gratings, one orientation
  per class, per-sample orientation jitter (Bayes ~0.93 at the default
  5 degrees), random phase/frequency + heavy pixel noise.  A CNN must
  learn orientation-selective filters; a linear pixel readout cannot.
* ``imdb_proxy`` — length-256 token sequences over the TextCNN's 20k
  vocab; each sequence plants 1+B(3,0.55) tokens from its class's
  100-token lexicon and B(3,0.3) confusers from the other class's
  (counting-oracle Bayes 0.914).  Max-pooled n-gram detection — the thing
  a Kim-2014 text-CNN does — is the solution shape.

Run:  python examples/accuracy.py [--epochs E] [--workers N] [--cpu 8]
Floors + gap bounds are asserted on the committed TPU artifact by
tests/test_accuracy_proxies.py; the artifact is ACCURACY_r04.json at the
repo root.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import numpy as np


def make_cifar_proxy(n: int, seed: int = 0, num_classes: int = 10,
                     jitter_deg: float = 5.0, noise: float = 0.25):
    """Oriented-grating images [n, 32, 32, 3] in [0, 1], labels [n].

    Deliberately NON-saturating (VERDICT r3 weak #1: the round-3 variant
    trained to 1.0, so "matched final accuracy" could not discriminate):
    classes are 18-degree-apart orientations and each sample's orientation
    is jittered by N(0, jitter_deg) — at 5 degrees the Bayes-optimal
    orientation decoder itself tops out near 0.93
    (P(|N(0,5)| < 9) = 0.928) — plus heavier pixel noise.  A trainer that
    under-trains or mis-averages now shows up as a visible accuracy gap
    instead of hiding at ceiling."""
    rng = np.random.default_rng(seed)
    y = rng.integers(0, num_classes, size=n)
    yy, xx = np.mgrid[0:32, 0:32].astype(np.float32)
    jitter = rng.normal(0.0, np.deg2rad(jitter_deg), size=n).astype(np.float32)
    theta = (y * np.pi / num_classes + jitter)[:, None, None].astype(np.float32)
    freq = rng.uniform(0.4, 0.7, size=(n, 1, 1)).astype(np.float32)
    phase = rng.uniform(0, 2 * np.pi, size=(n, 1, 1)).astype(np.float32)
    proj = xx[None] * np.cos(theta) + yy[None] * np.sin(theta)
    img = 0.5 + 0.5 * np.sin(freq * proj + phase)
    img = img[..., None].repeat(3, axis=-1)
    # per-channel colour jitter + pixel noise keep single pixels uninformative
    img *= rng.uniform(0.6, 1.0, size=(n, 1, 1, 3)).astype(np.float32)
    img += rng.normal(0, noise, size=img.shape).astype(np.float32)
    return np.clip(img, 0.0, 1.0).astype(np.float32), y.astype(np.int32)


def make_imdb_proxy(n: int, seed: int = 0, seq_len: int = 256,
                    vocab: int = 20000, lexicon: int = 100):
    """Token sequences [n, seq_len] int32, binary labels [n].

    Hardened like the grating proxy: each sequence plants ``1 + B(3, 0.55)``
    tokens from its OWN class lexicon and ``B(3, 0.3)`` confuser tokens from
    the OTHER class's lexicon at random positions among shared distractors.
    The Bayes decision (majority of lexicon hits, coin on ties) measures
    0.914 — the counting oracle in tests/test_accuracy_proxies.py — so a
    text-CNN that actually learns both lexicons lands high-80s/low-90s and
    a mis-tuned trainer visibly below, instead of everything saturating at
    0.99+ as in round 3."""
    rng = np.random.default_rng(seed)
    y = rng.integers(0, 2, size=n)
    # distractors avoid both lexica: tokens >= 1000
    x = rng.integers(1000, vocab, size=(n, seq_len))
    own_base = 100 + y * lexicon      # class 0 -> [100, 200), 1 -> [200, 300)
    other_base = 100 + (1 - y) * lexicon
    n_own = 1 + rng.binomial(3, 0.55, size=n)
    n_other = rng.binomial(3, 0.3, size=n)
    for i in range(n):
        k = n_own[i] + n_other[i]
        pos = rng.choice(seq_len, size=k, replace=False)
        own_toks = rng.integers(own_base[i], own_base[i] + lexicon, size=n_own[i])
        other_toks = rng.integers(other_base[i], other_base[i] + lexicon,
                                  size=n_other[i])
        x[i, pos] = np.concatenate([own_toks, other_toks])
    return x.astype(np.int32), y.astype(np.int32)


def _train_eval(trainer_cls, model, train_xy, test_xy, *,
                trainer_kwargs, batch_size, epochs, num_classes):
    import distkeras_tpu as dk

    (x_tr, y_tr), (x_te, y_te) = train_xy, test_xy
    df = dk.from_numpy(x_tr, y_tr)
    df = dk.OneHotTransformer(num_classes, input_col="label",
                              output_col="label_oh").transform(df)
    t = trainer_cls(model, loss="categorical_crossentropy",
                    features_col="features", label_col="label_oh",
                    batch_size=batch_size, num_epoch=epochs,
                    seed=0, **trainer_kwargs)
    trained = t.train(df)
    test_df = dk.from_numpy(x_te, y_te)
    pred = dk.ModelPredictor(trained, features_col="features").predict(test_df)
    pred = dk.LabelIndexTransformer(num_classes, input_col="prediction",
                                    output_col="pidx").transform(pred)
    acc = dk.AccuracyEvaluator(prediction_col="pidx",
                               label_col="label").evaluate(pred)
    return acc, t.get_training_time()


def trainer_table(dk, num_workers: int, window: int, lr: float = 1e-3):
    """All six trainer families with the LR discipline the digits experiment
    table established (examples/experiments.py): sum-commit rules divide the
    worker LR by N, ADAG rescales by window/N, the elastic pair keeps its
    own rho/lr.  One shared communication window keeps the comparison about
    the ALGORITHM, not the window."""
    adam = ("adam", {"learning_rate": lr})
    adam_sum = ("adam", {"learning_rate": lr / num_workers})
    nw = {"num_workers": num_workers}
    return [
        ("single", dk.SingleTrainer, {"worker_optimizer": adam}),
        ("downpour", dk.DOWNPOUR,
         {"worker_optimizer": adam_sum, "communication_window": window, **nw}),
        ("aeasgd", dk.AEASGD,
         {"worker_optimizer": adam, "communication_window": window,
          "rho": 1.0, "learning_rate": 0.05, **nw}),
        ("eamsgd", dk.EAMSGD,
         {"communication_window": window, "rho": 1.0, "learning_rate": 0.05,
          "momentum": 0.9, **nw}),
        ("adag", dk.ADAG,
         {"worker_optimizer": ("adam", {"learning_rate": lr * window / num_workers}),
          "communication_window": window, **nw}),
        ("dynsgd", dk.DynSGD,
         {"worker_optimizer": adam_sum, "communication_window": window, **nw}),
    ]


def try_real_cifar10():
    try:
        cache = os.path.expanduser("~/.keras/datasets/cifar-10-batches-py")
        if not os.path.isdir(cache):
            return None
        from keras.datasets import cifar10

        (x_tr, y_tr), (x_te, y_te) = cifar10.load_data()
        return ((x_tr.astype(np.float32) / 255.0, y_tr.ravel().astype(np.int32)),
                (x_te.astype(np.float32) / 255.0, y_te.ravel().astype(np.int32)),
                "cifar10")
    except Exception:
        return None


def try_real_imdb(seq_len=256, vocab=20000):
    try:
        cache = os.path.expanduser("~/.keras/datasets/imdb.npz")
        if not os.path.isfile(cache):
            return None
        from keras.datasets import imdb
        from keras.preprocessing.sequence import pad_sequences

        (x_tr, y_tr), (x_te, y_te) = imdb.load_data(num_words=vocab)
        pad = lambda x: pad_sequences(x, maxlen=seq_len).astype(np.int32)
        return ((pad(x_tr), y_tr.astype(np.int32)),
                (pad(x_te), y_te.astype(np.int32)), "imdb")
    except Exception:
        return None


def run_accuracy(num_workers=None, epochs=6, n_train=8192, n_test=2048,
                 batch_size=64, include=("cifar", "imdb"), window=None,
                 lr=1e-3, trainers=None):
    """Returns a list of result dicts — one per (dataset, trainer).

    VERDICT r3 item 1: ALL SIX trainer families run on both benchmark-model
    proxies, each row carrying its gap to SingleTrainer on the same data —
    the benchmark-scale analogue of the digits experiment table, on tasks
    hard enough (see the proxy docstrings) that the gaps mean something.
    """
    import jax

    import distkeras_tpu as dk
    from distkeras_tpu.models import CIFARCNN, FlaxModel, TextCNN

    num_workers = num_workers or jax.device_count()
    if window is None:
        # No larger than the per-worker steps in one epoch, so the wrap
        # padding to a window multiple doesn't multiply the work on small runs.
        steps_per_epoch = max(1, n_train // (num_workers * batch_size))
        window = max(1, min(4, steps_per_epoch))
    table = trainer_table(dk, num_workers, window, lr)
    if trainers:
        table = [row for row in table if row[0] in trainers]
    results = []

    datasets = []
    if "cifar" in include:
        real = try_real_cifar10()
        if real is not None:
            train, test, dataset = real
        else:
            train = make_cifar_proxy(n_train, seed=0)
            test = make_cifar_proxy(n_test, seed=1)
            dataset = "cifar_proxy"
        datasets.append((dataset, "cnn", train, test, 10,
                         lambda: FlaxModel(CIFARCNN())))
    if "imdb" in include:
        real = try_real_imdb()
        if real is not None:
            train, test, dataset = real
        else:
            train = make_imdb_proxy(n_train, seed=0)
            test = make_imdb_proxy(n_test, seed=1)
            dataset = "imdb_proxy"
        datasets.append((dataset, "textcnn", train, test, 2,
                         lambda: FlaxModel(TextCNN(vocab_size=20000,
                                                   num_classes=2))))

    for dataset, model_tag, train, test, classes, fresh_model in datasets:
        single_acc = None
        for name, cls, kw in table:
            acc, seconds = _train_eval(
                cls, fresh_model(), train, test,
                # full unroll of the per-step scan: math-invariant, and on
                # the CPU test mesh it sidesteps XLA:CPU's pathological
                # compile times for conv loops (WindowedEngine._finish_init)
                trainer_kwargs={**kw, "unroll": True},
                batch_size=batch_size, epochs=epochs, num_classes=classes)
            if name == "single":
                single_acc = acc
            row = {"metric": f"{dataset}_{model_tag}_{name}_accuracy",
                   "value": round(acc, 4), "unit": "test accuracy",
                   "trainer": name, "dataset": dataset, "epochs": epochs,
                   "num_workers": 1 if name == "single" else num_workers,
                   "train_seconds": round(seconds, 1)}
            if single_acc is not None and name != "single":
                row["gap_to_single"] = round(single_acc - acc, 4)
            results.append(row)
    return results


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--epochs", type=int, default=6)
    parser.add_argument("--train", type=int, default=8192)
    parser.add_argument("--test", type=int, default=2048)
    parser.add_argument("--batch-size", type=int, default=64)
    parser.add_argument("--workers", type=int, default=None)
    parser.add_argument("--window", type=int, default=None)
    parser.add_argument("--lr", type=float, default=1e-3)
    parser.add_argument("--include", type=str, default="cifar,imdb")
    parser.add_argument("--trainers", type=str, default="",
                        help="comma list (single,downpour,aeasgd,eamsgd,"
                        "adag,dynsgd); empty = all six")
    parser.add_argument("--cpu", type=int, default=0, metavar="N",
                        help="force an N-device CPU mesh (offline / no TPU)")
    args = parser.parse_args()

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", args.cpu)

    include = tuple(s.strip() for s in args.include.split(",") if s.strip())
    unknown = set(include) - {"cifar", "imdb"}
    if not include or unknown:
        parser.error(f"--include takes a comma list of cifar,imdb (got {args.include!r})")
    trainers = tuple(s.strip() for s in args.trainers.split(",") if s.strip()) or None
    for result in run_accuracy(args.workers, args.epochs, args.train,
                               args.test, args.batch_size,
                               include=include,
                               window=args.window, lr=args.lr,
                               trainers=trainers):
        result["backend"] = jax.default_backend()
        print(json.dumps(result))


if __name__ == "__main__":
    main()
