"""Accuracy proof on the benchmark models — the "matched final accuracy"
evidence BASELINE.json's north star demands (VERDICT r2 item 4, hardened
per VERDICT r3 item 1).

Trains ALL SIX trainer families (SingleTrainer + the five async
algorithms) plus a matched-optimizer momentum control on the
CIFAR-10-CNN-shaped and IMDB-TextCNN-shaped tasks end to end through the
DataFrame pipeline, printing one JSON line per (dataset, trainer) with
each async trainer's accuracy gap to its sequential yardstick on the same
data — the benchmark-scale analogue of the README's digits experiment
table (see ``trainer_table``/``run_accuracy`` for the measured per-task
tuning disciplines and the AEASGD characterization).

Datasets: real CIFAR-10 / IMDB when a local cache exists (keras.datasets;
this environment has no network), otherwise **deterministic learnable
proxies** of the same shape/scale, deliberately hardened so SingleTrainer
lands ~0.85-0.93 instead of saturating (a saturated task cannot detect an
async-accuracy regression — round 3's artifact read 1.0 / 0.997):

* ``cifar_proxy`` — 32x32x3 oriented sinusoidal gratings, one orientation
  per class, per-sample orientation jitter (Bayes ~0.93 at the default
  5 degrees), random phase/frequency + heavy pixel noise.  A CNN must
  learn orientation-selective filters; a linear pixel readout cannot.
* ``imdb_proxy`` — length-256 token sequences over the TextCNN's 20k
  vocab; each sequence plants 1+B(3,0.55) tokens from its class's
  100-token lexicon and B(3,0.3) confusers from the other class's
  (counting-oracle Bayes 0.914).  Max-pooled n-gram detection — the thing
  a Kim-2014 text-CNN does — is the solution shape.

Run:  python examples/accuracy.py [--epochs E] [--workers N] [--cpu 8]
Floors + gap bounds are asserted on the committed TPU artifact by
tests/test_accuracy_proxies.py; the artifact is ACCURACY_r05.json at the
repo root.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import numpy as np


def make_cifar_proxy(n: int, seed: int = 0, num_classes: int = 10,
                     jitter_deg: float = 5.0, noise: float = 0.25):
    """Oriented-grating images [n, 32, 32, 3] in [0, 1], labels [n].

    Deliberately NON-saturating (VERDICT r3 weak #1: the round-3 variant
    trained to 1.0, so "matched final accuracy" could not discriminate):
    classes are 18-degree-apart orientations and each sample's orientation
    is jittered by N(0, jitter_deg) — at 5 degrees the Bayes-optimal
    orientation decoder itself tops out near 0.93
    (P(|N(0,5)| < 9) = 0.928) — plus heavier pixel noise.  A trainer that
    under-trains or mis-averages now shows up as a visible accuracy gap
    instead of hiding at ceiling."""
    rng = np.random.default_rng(seed)
    y = rng.integers(0, num_classes, size=n)
    yy, xx = np.mgrid[0:32, 0:32].astype(np.float32)
    jitter = rng.normal(0.0, np.deg2rad(jitter_deg), size=n).astype(np.float32)
    theta = (y * np.pi / num_classes + jitter)[:, None, None].astype(np.float32)
    freq = rng.uniform(0.4, 0.7, size=(n, 1, 1)).astype(np.float32)
    phase = rng.uniform(0, 2 * np.pi, size=(n, 1, 1)).astype(np.float32)
    proj = xx[None] * np.cos(theta) + yy[None] * np.sin(theta)
    img = 0.5 + 0.5 * np.sin(freq * proj + phase)
    img = img[..., None].repeat(3, axis=-1)
    # per-channel colour jitter + pixel noise keep single pixels uninformative
    img *= rng.uniform(0.6, 1.0, size=(n, 1, 1, 3)).astype(np.float32)
    img += rng.normal(0, noise, size=img.shape).astype(np.float32)
    return np.clip(img, 0.0, 1.0).astype(np.float32), y.astype(np.int32)


def make_imdb_proxy(n: int, seed: int = 0, seq_len: int = 256,
                    vocab: int = 20000, lexicon: int = 100):
    """Token sequences [n, seq_len] int32, binary labels [n].

    Hardened like the grating proxy: each sequence plants ``1 + B(3, 0.55)``
    tokens from its OWN class lexicon and ``B(3, 0.3)`` confuser tokens from
    the OTHER class's lexicon at random positions among shared distractors.
    The Bayes decision (majority of lexicon hits, coin on ties) measures
    0.914 — the counting oracle in tests/test_accuracy_proxies.py — so a
    text-CNN that actually learns both lexicons lands high-80s/low-90s and
    a mis-tuned trainer visibly below, instead of everything saturating at
    0.99+ as in round 3."""
    rng = np.random.default_rng(seed)
    y = rng.integers(0, 2, size=n)
    # distractors avoid both lexica: tokens >= 1000
    x = rng.integers(1000, vocab, size=(n, seq_len))
    own_base = 100 + y * lexicon      # class 0 -> [100, 200), 1 -> [200, 300)
    other_base = 100 + (1 - y) * lexicon
    n_own = 1 + rng.binomial(3, 0.55, size=n)
    n_other = rng.binomial(3, 0.3, size=n)
    for i in range(n):
        k = n_own[i] + n_other[i]
        pos = rng.choice(seq_len, size=k, replace=False)
        own_toks = rng.integers(own_base[i], own_base[i] + lexicon, size=n_own[i])
        other_toks = rng.integers(other_base[i], other_base[i] + lexicon,
                                  size=n_other[i])
        x[i, pos] = np.concatenate([own_toks, other_toks])
    return x.astype(np.int32), y.astype(np.int32)


def _train_eval(trainer_cls, model, train_xy, test_xy, *,
                trainer_kwargs, batch_size, epochs, num_classes):
    import distkeras_tpu as dk

    (x_tr, y_tr), (x_te, y_te) = train_xy, test_xy
    df = dk.from_numpy(x_tr, y_tr)
    df = dk.OneHotTransformer(num_classes, input_col="label",
                              output_col="label_oh").transform(df)
    t = trainer_cls(model, loss="categorical_crossentropy",
                    features_col="features", label_col="label_oh",
                    batch_size=batch_size, num_epoch=epochs,
                    seed=0, **trainer_kwargs)
    trained = t.train(df)
    test_df = dk.from_numpy(x_te, y_te)
    pred = dk.ModelPredictor(trained, features_col="features").predict(test_df)
    pred = dk.LabelIndexTransformer(num_classes, input_col="prediction",
                                    output_col="pidx").transform(pred)
    acc = dk.AccuracyEvaluator(prediction_col="pidx",
                               label_col="label").evaluate(pred)
    return acc, t.get_training_time()


def trainer_table(dk, num_workers: int, dataset: str, max_window: int = None):
    """All six trainer families plus one matched-optimizer CONTROL, each at
    its task-tuned hyperparameters.  One lr-discipline-fits-all was this
    round's first artifact attempt and it mismeasured every family; every
    rule below is a TPU measurement (round-5 probe series), not a guess:

    * ``single`` — adam(1e-3), the standard yardstick (both tasks).
    * ``single_momentum`` — Nesterov SGD(0.01, 0.9): the matched-optimizer
      yardstick for EAMSGD, whose defining trait IS its momentum-SGD worker
      (reference ``EAMSGDWorker``).  Momentum-SGD alone tops out ~0.51 on
      the embedding task (adam: 0.81) — an *optimizer* deficit that a
      comparison against the adam single would misattribute to asynchrony.
    * ``downpour``/``dynsgd`` — adam sum-commits: lr/N on the conv task
      (undivided sums of N adam windows diverge there — measured 0.092) but
      UNDIVIDED lr on the embedding task (lr/N starves the rare embedding
      rows N-fold — measured 0.61 vs 0.79).  adam's step size is not linear
      in lr, so no single division rule is right across tasks.
    * ``adag`` — adam(lr*window) on BOTH tasks: its /window commit
      normalisation keeps the undivided rate stable even on the conv task
      (measured 0.911 cifar / 0.794 imdb — the strongest async family).
    * ``aeasgd`` — adam worker at the EASGD strong-coupling end
      (alpha = rho*lr = 0.25, N*alpha = 1.0): matches single on the conv
      task; carries a characterized exploration penalty on the embedding
      task (see ``run_accuracy``).
    * ``eamsgd`` — Nesterov(0.01, 0.9) worker with the same elastic
      coupling; judged against ``single_momentum``.
    """
    n01 = ("sgd", {"learning_rate": 0.01, "momentum": 0.9, "nesterov": True})
    nw = {"num_workers": num_workers}
    if dataset.startswith("cifar"):
        sum_lr = 1e-3 / num_workers  # divided: undivided diverges (0.092)
        aeasgd_opt = ("adam", {"learning_rate": 1e-3})
        aeasgd_win = 4
        eamsgd_rho = 5.0
    else:
        sum_lr = 1e-3  # undivided: /N starves rare embedding rows
        aeasgd_opt = ("adam", {"learning_rate": 2e-3})
        aeasgd_win = 8  # slower coupling measured best on sparse features
        eamsgd_rho = 2.5  # gentler pull: best gap to its momentum control
    adam_sum = ("adam", {"learning_rate": sum_lr})
    # Smoke runs (tiny --train) have fewer per-worker steps per epoch than
    # the tuned windows; clamping keeps the wrap padding to a window
    # multiple from silently multiplying the work (the artifact-scale run
    # has 32 steps/epoch per worker and is never clamped).
    clamp = (lambda w: max(1, min(w, max_window))) if max_window else (lambda w: w)
    aeasgd_win = clamp(aeasgd_win)
    return [
        ("single", dk.SingleTrainer,
         {"worker_optimizer": ("adam", {"learning_rate": 1e-3})}),
        ("single_momentum", dk.SingleTrainer, {"worker_optimizer": n01}),
        ("downpour", dk.DOWNPOUR,
         {"worker_optimizer": adam_sum, "communication_window": clamp(4), **nw}),
        ("aeasgd", dk.AEASGD,
         {"worker_optimizer": aeasgd_opt, "communication_window": aeasgd_win,
          "rho": 5.0, "learning_rate": 0.05, **nw}),
        ("eamsgd", dk.EAMSGD,
         {"worker_optimizer": n01, "communication_window": clamp(4),
          "rho": eamsgd_rho, "learning_rate": 0.05, "momentum": 0.9, **nw}),
        ("adag", dk.ADAG,
         {"worker_optimizer": ("adam", {"learning_rate": 4e-3}),
          "communication_window": clamp(4), **nw}),
        ("dynsgd", dk.DynSGD,
         {"worker_optimizer": adam_sum, "communication_window": clamp(4), **nw}),
    ]


def try_real_cifar10():
    try:
        cache = os.path.expanduser("~/.keras/datasets/cifar-10-batches-py")
        if not os.path.isdir(cache):
            return None
        from keras.datasets import cifar10

        (x_tr, y_tr), (x_te, y_te) = cifar10.load_data()
        return ((x_tr.astype(np.float32) / 255.0, y_tr.ravel().astype(np.int32)),
                (x_te.astype(np.float32) / 255.0, y_te.ravel().astype(np.int32)),
                "cifar10")
    except Exception:
        return None


def try_real_imdb(seq_len=256, vocab=20000):
    try:
        cache = os.path.expanduser("~/.keras/datasets/imdb.npz")
        if not os.path.isfile(cache):
            return None
        from keras.datasets import imdb
        from keras.preprocessing.sequence import pad_sequences

        (x_tr, y_tr), (x_te, y_te) = imdb.load_data(num_words=vocab)
        pad = lambda x: pad_sequences(x, maxlen=seq_len).astype(np.int32)
        return ((pad(x_tr), y_tr.astype(np.int32)),
                (pad(x_te), y_te.astype(np.int32)), "imdb")
    except Exception:
        return None


def run_accuracy(num_workers=None, epochs=16, n_train=8192, n_test=2048,
                 batch_size=64, include=("cifar", "imdb"), trainers=None):
    """Returns a list of result dicts — one per (dataset, trainer/control).

    VERDICT r3 item 1 / r4 item 1: ALL SIX trainer families on both
    benchmark-model proxies, each async row carrying its gap to the right
    sequential yardstick on the same data — ``gap_to_single`` (adam
    SingleTrainer) for the adam-worker families, plus ``gap_to_control``
    (``single_momentum``) for EAMSGD, whose momentum-SGD worker must not
    have its optimizer's deficit billed to asynchrony.

    Characterized exception (the hardened proxies doing their job): AEASGD
    on the sparse-embedding task.  Its elastic force is the ONLY coupling
    (workers never pull — reference semantics), so consensus on rarely-
    updated embedding rows forms slowly; across the probed surface
    (rho 1-10, tau 1-16, adam lr 1e-3..3e-3, epochs 16..96, TPU round 5)
    it plateaus ~4-9 points under the adam single while the SAME config
    family MATCHES single on the dense conv task.  The committed artifact
    records the measured gap; tests/test_accuracy_proxies.py bounds it as
    a regression guard (floor + max-gap) instead of hiding it — matching
    the EASGD paper's own dense-vision scope.
    """
    import jax

    import distkeras_tpu as dk
    from distkeras_tpu.models import CIFARCNN, FlaxModel, TextCNN

    num_workers = num_workers or jax.device_count()
    results = []

    datasets = []
    if "cifar" in include:
        real = try_real_cifar10()
        if real is not None:
            train, test, dataset = real
        else:
            train = make_cifar_proxy(n_train, seed=0)
            test = make_cifar_proxy(n_test, seed=1)
            dataset = "cifar_proxy"
        datasets.append((dataset, "cnn", train, test, 10,
                         lambda: FlaxModel(CIFARCNN())))
    if "imdb" in include:
        real = try_real_imdb()
        if real is not None:
            train, test, dataset = real
        else:
            train = make_imdb_proxy(n_train, seed=0)
            test = make_imdb_proxy(n_test, seed=1)
            dataset = "imdb_proxy"
        datasets.append((dataset, "textcnn", train, test, 2,
                         lambda: FlaxModel(TextCNN(vocab_size=20000,
                                                   num_classes=2))))

    for dataset, model_tag, train, test, classes, fresh_model in datasets:
        steps_per_epoch = max(1, n_train // (num_workers * batch_size))
        table = trainer_table(dk, num_workers, dataset,
                              max_window=steps_per_epoch)
        if trainers:
            table = [row for row in table if row[0] in trainers]
        single_acc, control_acc = None, None
        for name, cls, kw in table:
            # Unroll policy is per-backend: full unroll is math-invariant
            # and sidesteps XLA:CPU's pathological compile times for conv
            # loops (WindowedEngine._finish_init) — but on TPU it bloats the
            # program (SingleTrainer: 128 unrolled conv train steps) into
            # minutes of tracing through the tunnel, where the rolled scan
            # compiles in seconds and runs at the same speed.
            unroll = True if jax.default_backend() == "cpu" else 1
            acc, seconds = _train_eval(
                cls, fresh_model(), train, test,
                trainer_kwargs={**kw, "unroll": unroll},
                batch_size=batch_size, epochs=epochs, num_classes=classes)
            sequential = name in ("single", "single_momentum")
            if name == "single":
                single_acc = acc
            if name == "single_momentum":
                control_acc = acc
            row = {"metric": f"{dataset}_{model_tag}_{name}_accuracy",
                   "value": round(acc, 4), "unit": "test accuracy",
                   "trainer": name, "dataset": dataset, "epochs": epochs,
                   "num_workers": 1 if sequential else num_workers,
                   "train_seconds": round(seconds, 1)}
            if not sequential:
                if single_acc is not None:
                    row["gap_to_single"] = round(single_acc - acc, 4)
                if name == "eamsgd" and control_acc is not None:
                    # the matched-optimizer yardstick (see trainer_table)
                    row["gap_to_control"] = round(control_acc - acc, 4)
            results.append(row)
    return results


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--epochs", type=int, default=16)
    parser.add_argument("--train", type=int, default=8192)
    parser.add_argument("--test", type=int, default=2048)
    parser.add_argument("--batch-size", type=int, default=64)
    parser.add_argument("--workers", type=int, default=None)
    parser.add_argument("--trainers", type=str, default="",
                        help="comma list (single,single_momentum,downpour,"
                        "aeasgd,eamsgd,adag,dynsgd); empty = all")
    parser.add_argument("--include", type=str, default="cifar,imdb")
    parser.add_argument("--cpu", type=int, default=0, metavar="N",
                        help="force an N-device CPU mesh (offline / no TPU)")
    args = parser.parse_args()

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", args.cpu)

    include = tuple(s.strip() for s in args.include.split(",") if s.strip())
    unknown = set(include) - {"cifar", "imdb"}
    if not include or unknown:
        parser.error(f"--include takes a comma list of cifar,imdb (got {args.include!r})")
    trainers = tuple(s.strip() for s in args.trainers.split(",") if s.strip()) or None
    for result in run_accuracy(args.workers, args.epochs, args.train,
                               args.test, args.batch_size,
                               include=include, trainers=trainers):
        result["backend"] = jax.default_backend()
        print(json.dumps(result))


if __name__ == "__main__":
    main()
