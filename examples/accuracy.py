"""Accuracy proof on the benchmark models — the "matched final accuracy"
evidence BASELINE.json's north star demands (VERDICT r2 item 4).

Trains the CIFAR-10 CNN (DOWNPOUR — the headline config) and the IMDB
TextCNN (DynSGD) end to end through the DataFrame pipeline to asserted
accuracy floors, printing one JSON line per model.

Datasets: real CIFAR-10 / IMDB when a local cache exists (keras.datasets;
this environment has no network), otherwise **deterministic learnable
proxies** of the same shape/scale:

* ``cifar_proxy`` — 32x32x3 oriented sinusoidal gratings, one orientation
  per class, random phase/frequency jitter + Gaussian pixel noise.  A CNN
  must learn orientation-selective filters (exactly what its early conv
  layers are for); a linear readout of raw pixels cannot average out the
  random phases.
* ``imdb_proxy`` — length-256 token sequences over the TextCNN's 20k vocab;
  each class plants a handful of tokens from its own 100-token lexicon at
  random positions in a stream of shared distractor tokens.  Max-pooled
  n-gram detection — the thing a Kim-2014 text-CNN does — solves it;
  counting raw token statistics barely beats chance because lexicon tokens
  are rare and positions random.

Run:  python examples/accuracy.py [--epochs E] [--train N] [--cpu 8]
Floors are asserted by tests/test_accuracy_proxies.py on the CPU mesh; the
TPU-side artifact is ACCURACY_r03.json at the repo root.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import numpy as np


def make_cifar_proxy(n: int, seed: int = 0, num_classes: int = 10):
    """Oriented-grating images [n, 32, 32, 3] in [0, 1], labels [n]."""
    rng = np.random.default_rng(seed)
    y = rng.integers(0, num_classes, size=n)
    yy, xx = np.mgrid[0:32, 0:32].astype(np.float32)
    theta = (y[:, None, None] * np.pi / num_classes).astype(np.float32)
    freq = rng.uniform(0.4, 0.7, size=(n, 1, 1)).astype(np.float32)
    phase = rng.uniform(0, 2 * np.pi, size=(n, 1, 1)).astype(np.float32)
    proj = xx[None] * np.cos(theta) + yy[None] * np.sin(theta)
    img = 0.5 + 0.5 * np.sin(freq * proj + phase)
    img = img[..., None].repeat(3, axis=-1)
    # per-channel colour jitter + pixel noise keep single pixels uninformative
    img *= rng.uniform(0.6, 1.0, size=(n, 1, 1, 3)).astype(np.float32)
    img += rng.normal(0, 0.15, size=img.shape).astype(np.float32)
    return np.clip(img, 0.0, 1.0).astype(np.float32), y.astype(np.int32)


def make_imdb_proxy(n: int, seed: int = 0, seq_len: int = 256,
                    vocab: int = 20000, lexicon: int = 100, planted: int = 6):
    """Token sequences [n, seq_len] int32, binary labels [n]."""
    rng = np.random.default_rng(seed)
    y = rng.integers(0, 2, size=n)
    # distractors avoid both lexica: tokens >= 1000
    x = rng.integers(1000, vocab, size=(n, seq_len))
    base = 100 + y * lexicon  # class 0 -> [100, 200), class 1 -> [200, 300)
    for i in range(n):
        pos = rng.choice(seq_len, size=planted, replace=False)
        x[i, pos] = rng.integers(base[i], base[i] + lexicon, size=planted)
    return x.astype(np.int32), y.astype(np.int32)


def _train_eval(trainer_cls, model, train_xy, test_xy, *, num_workers,
                trainer_kwargs, batch_size, epochs, num_classes):
    import distkeras_tpu as dk

    (x_tr, y_tr), (x_te, y_te) = train_xy, test_xy
    df = dk.from_numpy(x_tr, y_tr)
    df = dk.OneHotTransformer(num_classes, input_col="label",
                              output_col="label_oh").transform(df)
    t = trainer_cls(model, loss="categorical_crossentropy",
                    features_col="features", label_col="label_oh",
                    batch_size=batch_size, num_epoch=epochs,
                    num_workers=num_workers, seed=0, **trainer_kwargs)
    trained = t.train(df)
    test_df = dk.from_numpy(x_te, y_te)
    pred = dk.ModelPredictor(trained, features_col="features").predict(test_df)
    pred = dk.LabelIndexTransformer(num_classes, input_col="prediction",
                                    output_col="pidx").transform(pred)
    acc = dk.AccuracyEvaluator(prediction_col="pidx",
                               label_col="label").evaluate(pred)
    return acc, t.get_training_time()


def try_real_cifar10():
    try:
        cache = os.path.expanduser("~/.keras/datasets/cifar-10-batches-py")
        if not os.path.isdir(cache):
            return None
        from keras.datasets import cifar10

        (x_tr, y_tr), (x_te, y_te) = cifar10.load_data()
        return ((x_tr.astype(np.float32) / 255.0, y_tr.ravel().astype(np.int32)),
                (x_te.astype(np.float32) / 255.0, y_te.ravel().astype(np.int32)),
                "cifar10")
    except Exception:
        return None


def try_real_imdb(seq_len=256, vocab=20000):
    try:
        cache = os.path.expanduser("~/.keras/datasets/imdb.npz")
        if not os.path.isfile(cache):
            return None
        from keras.datasets import imdb
        from keras.preprocessing.sequence import pad_sequences

        (x_tr, y_tr), (x_te, y_te) = imdb.load_data(num_words=vocab)
        pad = lambda x: pad_sequences(x, maxlen=seq_len).astype(np.int32)
        return ((pad(x_tr), y_tr.astype(np.int32)),
                (pad(x_te), y_te.astype(np.int32)), "imdb")
    except Exception:
        return None


def run_accuracy(num_workers=None, epochs=4, n_train=8192, n_test=2048,
                 batch_size=64, include=("cifar", "imdb"), window=None,
                 lr=1e-3):
    """Returns a list of result dicts (one per model)."""
    import jax

    import distkeras_tpu as dk
    from distkeras_tpu.models import CIFARCNN, FlaxModel, TextCNN

    num_workers = num_workers or jax.device_count()
    if window is None:
        # No larger than the per-worker steps in one epoch, so the wrap
        # padding to a window multiple doesn't multiply the work on small runs.
        steps_per_epoch = max(1, n_train // (num_workers * batch_size))
        window = max(1, min(4, steps_per_epoch))
    results = []

    if "cifar" in include:
        real = try_real_cifar10()
        if real is not None:
            train, test, dataset = real
        else:
            train = make_cifar_proxy(n_train, seed=0)
            test = make_cifar_proxy(n_test, seed=1)
            dataset = "cifar_proxy"
        acc, seconds = _train_eval(
            dk.DOWNPOUR, FlaxModel(CIFARCNN()), train, test,
            num_workers=num_workers,
            trainer_kwargs={
                # DOWNPOUR's commit adds the SUM of worker deltas to the
                # center, so the worker lr divides by the worker count to keep
                # the center step at ``lr`` (the mis-tuning VERDICT r2 item 4
                # flagged on the digits table).
                "worker_optimizer": ("adam", {"learning_rate": lr / num_workers}),
                "communication_window": window,
                # full unroll of the per-step scan: math-invariant, and on the
                # CPU test mesh it sidesteps XLA:CPU's pathological compile
                # times for conv loops (see WindowedEngine._finish_init)
                "unroll": True,
            },
            batch_size=batch_size, epochs=epochs, num_classes=10)
        results.append({"metric": f"{dataset}_cnn_downpour_accuracy",
                        "value": round(acc, 4), "unit": "test accuracy",
                        "dataset": dataset, "epochs": epochs,
                        "train_seconds": round(seconds, 1)})

    if "imdb" in include:
        real = try_real_imdb()
        if real is not None:
            train, test, dataset = real
        else:
            train = make_imdb_proxy(n_train, seed=0)
            test = make_imdb_proxy(n_test, seed=1)
            dataset = "imdb_proxy"
        acc, seconds = _train_eval(
            dk.DynSGD, FlaxModel(TextCNN(vocab_size=20000, num_classes=2)),
            train, test, num_workers=num_workers,
            trainer_kwargs={
                # DynSGD divides each delta by (staleness+1) itself, but with
                # uniform windows every worker has staleness 0 — same sum-of-
                # deltas scaling as DOWNPOUR, same lr correction.
                "worker_optimizer": ("adam", {"learning_rate": lr / num_workers}),
                "communication_window": window,
                "unroll": True,
            },
            batch_size=batch_size, epochs=epochs, num_classes=2)
        results.append({"metric": f"{dataset}_textcnn_dynsgd_accuracy",
                        "value": round(acc, 4), "unit": "test accuracy",
                        "dataset": dataset, "epochs": epochs,
                        "train_seconds": round(seconds, 1)})
    return results


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--epochs", type=int, default=4)
    parser.add_argument("--train", type=int, default=8192)
    parser.add_argument("--test", type=int, default=2048)
    parser.add_argument("--batch-size", type=int, default=64)
    parser.add_argument("--workers", type=int, default=None)
    parser.add_argument("--window", type=int, default=None)
    parser.add_argument("--lr", type=float, default=1e-3)
    parser.add_argument("--include", type=str, default="cifar,imdb")
    parser.add_argument("--cpu", type=int, default=0, metavar="N",
                        help="force an N-device CPU mesh (offline / no TPU)")
    args = parser.parse_args()

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", args.cpu)

    include = tuple(s.strip() for s in args.include.split(",") if s.strip())
    unknown = set(include) - {"cifar", "imdb"}
    if not include or unknown:
        parser.error(f"--include takes a comma list of cifar,imdb (got {args.include!r})")
    for result in run_accuracy(args.workers, args.epochs, args.train,
                               args.test, args.batch_size,
                               include=include,
                               window=args.window, lr=args.lr):
        result["backend"] = jax.default_backend()
        print(json.dumps(result))


if __name__ == "__main__":
    main()
