"""High-throughput streaming inference — the reference's Kafka pipeline
notebook (``examples/`` Kafka producer + inference consumer) without the
Kafka dependency.

Default: a producer thread emits feature batches onto a queue (stand-in for
a Kafka topic; swap in ``kafka-python`` consumers unchanged — the prediction
loop only sees an iterator of batches).  With ``--source tcp://host:port``
the consumer instead drains a *separate producer process*
(``examples/kafka_producer.py``) over the package wire codec — the real
cross-process pipeline.  Either way the consumer runs the jitted model
forward pass per batch and reports sustained rows/sec.
"""

import argparse
import os
import queue
import sys
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np


def tcp_batches(addr: str):
    """Yield batches from a kafka_producer.py --port serving at tcp://host:port."""
    from distkeras_tpu.networking import connect, recv_data

    host, port = addr.removeprefix("tcp://").rsplit(":", 1)
    sock = connect(host, int(port))
    try:
        while True:
            batch = recv_data(sock)
            if batch is None:
                return
            yield batch
    finally:
        sock.close()


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--source", default=None,
                        help="tcp://host:port of a running kafka_producer.py "
                             "(default: in-process producer thread)")
    args = parser.parse_args()
    import distkeras_tpu as dk
    from distkeras_tpu.models import MLP, FlaxModel
    from distkeras_tpu.predictors import ModelPredictor

    # Train a small model first (the pipeline's "offline" phase).
    rng = np.random.default_rng(0)
    x = rng.normal(size=(4096, 32)).astype(np.float32)
    w = rng.normal(size=(32, 4))
    y = np.argmax(x @ w, axis=1).astype(np.int32)
    df = dk.from_numpy(x, y)
    df = dk.OneHotTransformer(4, input_col="label", output_col="label_oh").transform(df)
    trained = dk.SingleTrainer(FlaxModel(MLP(features=(64,), num_classes=4)),
                               loss="categorical_crossentropy",
                               worker_optimizer=("sgd", {"learning_rate": 0.1}),
                               label_col="label_oh", batch_size=64,
                               num_epoch=3).train(df)
    predictor = ModelPredictor(trained, batch_size=1024)

    if args.source:
        stream = tcp_batches(args.source)
    else:
        # "Kafka topic": a bounded queue fed by a producer thread.
        topic: "queue.Queue" = queue.Queue(maxsize=64)
        n_batches, batch_rows = 200, 1024

        def producer():
            for _ in range(n_batches):
                topic.put(rng.normal(size=(batch_rows, 32)).astype(np.float32))
            topic.put(None)  # end-of-stream marker

        threading.Thread(target=producer, daemon=True).start()
        stream = iter(topic.get, None)

    rows = 0
    t0 = time.perf_counter()
    for batch in stream:
        out = predictor.predict(dk.from_numpy(batch))
        rows += len(out)
    dt = time.perf_counter() - t0
    print(f"streamed {rows} rows in {dt:.2f}s -> {rows/dt:,.0f} rows/sec")


if __name__ == "__main__":
    main()
