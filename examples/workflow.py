"""API tour — the reference's ``workflow.ipynb`` as a runnable script.

Walks every public surface: DataFrame construction, transformers, all trainer
families, prediction, evaluation, serialization, and checkpoint/resume.
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np


def main():
    import jax

    import distkeras_tpu as dk
    from distkeras_tpu.models import MLP, FlaxModel

    print(f"backend: {jax.default_backend()}, devices: {jax.device_count()}")

    # ---- DataFrames ------------------------------------------------------
    rng = np.random.default_rng(0)
    n = 2048
    x = rng.normal(size=(n, 16)).astype(np.float32)
    w = rng.normal(size=(16, 3))
    y = np.argmax(x @ w + 0.3 * rng.normal(size=(n, 3)), axis=1).astype(np.int32)
    df = dk.from_numpy(x, y)
    print(df)

    # row-wise access, Spark style
    first = df.first()
    print("first row label:", first.label)

    # ---- Transformers ----------------------------------------------------
    df = dk.StandardScaleTransformer(input_col="features",
                                     output_col="features_std").transform(df)
    df = dk.OneHotTransformer(3, input_col="label",
                              output_col="label_oh").transform(df)
    train_df, test_df = df.split(0.85, seed=1)

    def fresh():
        return FlaxModel(MLP(features=(32,), num_classes=3))

    common = dict(loss="categorical_crossentropy",
                  worker_optimizer=("sgd", {"learning_rate": 0.1}),
                  features_col="features_std", label_col="label_oh",
                  batch_size=32, num_epoch=5)

    # ---- Every trainer family -------------------------------------------
    workers = min(4, jax.device_count())
    trainers = {
        "SingleTrainer": dk.SingleTrainer(fresh(), **common),
        "AveragingTrainer": dk.AveragingTrainer(fresh(), num_workers=workers, **common),
        "DOWNPOUR": dk.DOWNPOUR(fresh(), num_workers=workers,
                                communication_window=5, **common),
        "AEASGD": dk.AEASGD(fresh(), num_workers=workers,
                            communication_window=8, rho=1.0, learning_rate=0.05, **common),
        "EAMSGD": dk.EAMSGD(fresh(), num_workers=workers,
                            communication_window=8, rho=1.0, learning_rate=0.05,
                            momentum=0.8, **common),
        "ADAG": dk.ADAG(fresh(), num_workers=workers,
                        communication_window=8, **common),
        "DynSGD": dk.DynSGD(fresh(), num_workers=workers,
                            communication_window=5, **common),
    }
    for name, trainer in trainers.items():
        trained = trainer.train(train_df)
        pred = dk.ModelPredictor(trained, features_col="features_std").predict(test_df)
        pred = dk.LabelIndexTransformer(3, input_col="prediction",
                                        output_col="pidx").transform(pred)
        acc = dk.AccuracyEvaluator(prediction_col="pidx", label_col="label").evaluate(pred)
        print(f"{name:<18} acc={acc:.4f} time={trainer.get_training_time():.2f}s")

    # ---- Ensembles -------------------------------------------------------
    ensemble = dk.EnsembleTrainer(fresh(), num_models=3, **common).train(train_df)
    print(f"ensemble of {len(ensemble)} models trained")

    # ---- Checkpoint / resume --------------------------------------------
    with tempfile.TemporaryDirectory() as ckpt_dir:
        t = dk.DOWNPOUR(fresh(), num_workers=workers, communication_window=5,
                        checkpoint_dir=ckpt_dir, **common)
        t.train(train_df)
        from distkeras_tpu.checkpoint import latest_step

        print("checkpoints up to epoch:", latest_step(ckpt_dir))

    print("workflow complete")


if __name__ == "__main__":
    main()
