"""Standalone feature-batch producer — the reference's ``kafka_producer.py``
companion script for the high-throughput inference pipeline.

Two transports:

* **kafka** (``--bootstrap-servers host:9092 --topic features``): publishes
  npz-encoded batches through ``kafka-python`` when it's installed — the
  reference's original transport, unchanged.
* **tcp** (default): serves batches over a plain socket with the package's
  own length-prefixed codec (``distkeras_tpu.networking.send_data`` — no
  pickle), so the producer/consumer split is demonstrable across real
  processes with zero external infrastructure:

      terminal 1:  python examples/kafka_producer.py --port 9092
      terminal 2:  python examples/streaming_inference.py --source tcp://127.0.0.1:9092

End-of-stream markers: the TCP transport sends a codec-encoded ``None``;
the Kafka transport publishes one empty message (``b""``) — check for an
empty payload in a kafka-python consumer.
"""

import argparse
import os
import socket
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np


def batches(n_batches: int, rows: int, features: int, seed: int = 0):
    rng = np.random.default_rng(seed)
    for _ in range(n_batches):
        yield rng.normal(size=(rows, features)).astype(np.float32)


def produce_tcp(port: int, n_batches: int, rows: int, features: int) -> None:
    from distkeras_tpu.networking import send_data

    server = socket.socket()
    server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    server.bind(("0.0.0.0", port))
    server.listen(1)
    print(f"producer: serving {n_batches} x {rows} rows on :{port} ...")
    conn, addr = server.accept()
    print(f"producer: consumer connected from {addr[0]}")
    sent = 0
    with conn:
        for batch in batches(n_batches, rows, features):
            send_data(conn, batch)
            sent += len(batch)
        send_data(conn, None)  # end-of-stream
    server.close()
    print(f"producer: done, {sent} rows")


def produce_kafka(bootstrap: str, topic: str, n_batches: int, rows: int, features: int) -> None:
    import io

    from kafka import KafkaProducer  # the reference's transport

    producer = KafkaProducer(bootstrap_servers=bootstrap)
    for batch in batches(n_batches, rows, features):
        buf = io.BytesIO()
        np.save(buf, batch)
        producer.send(topic, buf.getvalue())
    producer.send(topic, b"")  # end-of-stream
    producer.flush()
    print(f"producer: published {n_batches} batches to {topic}")


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--port", type=int, default=9092)
    parser.add_argument("--batches", type=int, default=200)
    parser.add_argument("--rows", type=int, default=1024)
    parser.add_argument("--features", type=int, default=32)
    parser.add_argument("--bootstrap-servers", default=None,
                        help="use a real Kafka cluster (needs kafka-python)")
    parser.add_argument("--topic", default="features")
    args = parser.parse_args()
    if args.bootstrap_servers:
        produce_kafka(args.bootstrap_servers, args.topic,
                      args.batches, args.rows, args.features)
    else:
        produce_tcp(args.port, args.batches, args.rows, args.features)


if __name__ == "__main__":
    main()
