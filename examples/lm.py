"""Causal language modeling across the parallelism axes.

Beyond the reference's classifier-only scope: trains a small causal
transformer LM on a synthetic next-token corpus five ways —

  1. data parallel            (TransformerLM, 4 workers)
  2. + sequence parallelism   (causal ring attention, per-token labels
                               sharded over the seq axis with the tokens)
  3. pipeline parallel        (StagedLM: GPipe-for-LM, 4 workers x 2 stages)
  4. tp + FSDP center         (GSPMD engine: embedding/head center copies
                               sharded over workers AND model axes)
  5. HuggingFace fine-tune    (a transformers FlaxGPT2LMHeadModel through
                               the same trainer — its params are the
                               initial center, as from_pretrained's would be)
  6. GPT-2 on the pipeline    (gpt2_to_staged re-lays the checkpoint into
                               the staged layout; pipeline_stages=2 +
                               fsdp=True stage-shards embed/head; decode
                               through the pipelined executor)

— then greedily generates from the trained model with a carried KV cache
(one jitted prefill + scan program; see distkeras_tpu/models/generate.py).  Runs on a faked
8-device CPU mesh so it works anywhere (delete the two config lines on
real chips).

Run:  python examples/lm.py [--epochs E]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import jax

if os.environ.get("DK_TPU") != "1":  # delete these two lines on real chips
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 8)

import numpy as np

VOCAB = 23
SEQ = 16


def corpus(n=512, seed=0):
    """Next token = (token + 1) mod VOCAB, random start per sequence."""
    rng = np.random.default_rng(seed)
    start = rng.integers(0, VOCAB, size=(n, 1))
    x = ((start + np.arange(SEQ)) % VOCAB).astype(np.int32)
    return x, ((x + 1) % VOCAB).astype(np.int32)


def generate(model, ctx, steps=6):
    """KV-cached greedy decode (models/generate.py): prefill + scanned
    single-token steps in one jitted program, O(context) per step instead of
    the O(context^2) full recompute — token-identical to it
    (tests/test_generate.py)."""
    from distkeras_tpu.models import greedy_generate

    return greedy_generate(model, ctx, steps)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--epochs", type=int, default=12)
    args = parser.parse_args()

    import distkeras_tpu as dk
    from distkeras_tpu.models import FlaxModel, StagedLM, TransformerLM

    x, y = corpus()
    df = dk.from_numpy(x, y)
    common = dict(loss="token_crossentropy", metrics=("token_accuracy",),
                  batch_size=16, num_epoch=args.epochs,
                  communication_window=2)

    def report(tag, trainer):
        trained = trainer.train(df)
        h = trainer.get_history()
        print(f"{tag:32s} loss {h['loss'][0]:.2f}->{h['loss'][-1]:.3f} "
              f"token-acc {h['token_accuracy'][-1]:.3f} "
              f"time {trainer.get_training_time():.1f}s")
        return trained

    report("LM data parallel (4w)", dk.DOWNPOUR(
        FlaxModel(TransformerLM(vocab_size=VOCAB, dim=32, heads=2,
                                num_layers=1, max_len=64)),
        worker_optimizer=("adam", {"learning_rate": 1e-3}),
        num_workers=4, **common))

    report("LM + ring attention (4w x 2seq)", dk.DOWNPOUR(
        FlaxModel(TransformerLM(vocab_size=VOCAB, dim=32, heads=2,
                                num_layers=1, max_len=64, seq_axis="seq")),
        worker_optimizer=("adam", {"learning_rate": 1e-3}),
        num_workers=4, seq_shards=2, **common))

    trained = report("LM pipeline (4w x 2 stages)", dk.DOWNPOUR(
        StagedLM(vocab_size=VOCAB, dim=32, heads=2, num_stages=2,
                 blocks_per_stage=1, max_len=64),
        worker_optimizer=("adam", {"learning_rate": 1e-3}),
        num_workers=4, pipeline_stages=2, **common))

    # FSDP: the LM's embedding + output head dominate its params — with
    # fsdp=True their center copies shard over the workers axis instead of
    # replicating (ZeRO-3 gather-at-use), here composed with 2-way TP
    report("LM + fsdp center (4w x 2mp)", dk.DOWNPOUR(
        FlaxModel(TransformerLM(vocab_size=VOCAB, dim=32, heads=2,
                                num_layers=1, max_len=64)),
        worker_optimizer=("adam", {"learning_rate": 1e-3}),
        num_workers=4, tp_shards=2, fsdp=True, **common))

    # 5. a HuggingFace Flax model through the identical trainer call —
    #    swap the config-initialised model for .from_pretrained(...) to
    #    fine-tune a real checkpoint
    try:
        from transformers import FlaxGPT2LMHeadModel, GPT2Config
    except ImportError:
        print("transformers not installed -- skipping the HF variant")
    else:
        hf = FlaxGPT2LMHeadModel(
            GPT2Config(vocab_size=VOCAB, n_positions=SEQ, n_embd=32,
                       n_layer=1, n_head=2, resid_pdrop=0.0,
                       embd_pdrop=0.0, attn_pdrop=0.0),
            seed=0, input_shape=(1, 8))
        report("HF GPT-2 fine-tune (4w)", dk.DOWNPOUR(
            hf, worker_optimizer=("adam", {"learning_rate": 3e-3}),
            num_workers=4, **common))

        # 6. the same checkpoint ONTO THE PIPELINE MESH: gpt2_to_staged
        #    re-lays the weights into the staged layout (logit-identical —
        #    tests/test_hf_staged.py), fsdp=True stage-shards the
        #    vocab-scale embedding/head, and decode runs through the
        #    pipelined executor (one stage's blocks + KV cache per device)
        from distkeras_tpu.models import gpt2_to_staged
        from distkeras_tpu.models.generate import greedy_generate_staged_pipelined

        hf2 = FlaxGPT2LMHeadModel(
            GPT2Config(vocab_size=VOCAB, n_positions=SEQ, n_embd=32,
                       n_layer=2, n_head=2, resid_pdrop=0.0,
                       embd_pdrop=0.0, attn_pdrop=0.0),
            seed=0, input_shape=(1, 8))
        staged = gpt2_to_staged(hf2, num_stages=2)
        tuned = report("GPT-2 on pipeline+fsdp (4w x 2st)", dk.DOWNPOUR(
            staged, worker_optimizer=("adam", {"learning_rate": 3e-3}),
            num_workers=4, pipeline_stages=2, fsdp=True, **common))
        pp_ctx = greedy_generate_staged_pipelined(
            staged, tuned.params, x[:1, :8], 6, devices=jax.devices()[:2])
        print("pipelined GPT-2 generation:", pp_ctx[0, 8:])

    ctx = generate(trained, x[:1, :8])
    print("greedy generation:", ctx[0, 8:], "from context ending at", ctx[0, 7])


if __name__ == "__main__":
    main()
