"""MNIST end-to-end pipeline — the reference's flagship example, TPU-native.

Mirrors ``examples/mnist.py`` / ``mnist.ipynb`` of dist-keras: read the raw
dataset into a DataFrame, normalise + one-hot with transformers, train with
SingleTrainer then the async trainers (DOWNPOUR, AEASGD, ADAG), then predict
and evaluate — the whole flow staying on DataFrames.

Run:  python examples/mnist.py [--workers N] [--epochs E]

Dataset: uses ``keras.datasets.mnist`` when the archive is cached locally;
otherwise falls back to scikit-learn's bundled 8x8 digits (offline-friendly),
which exercises the identical pipeline at smaller scale.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np


def load_dataset(force_digits: bool = False):
    """Returns (name, features, labels, max_value, image_shape).

    Only uses MNIST when the archive is already cached: ``load_data()`` would
    otherwise try to download, which hangs in offline environments.
    ``force_digits`` pins the scikit-learn fallback regardless of cache state
    (tests need machine-independent data).
    """
    cache = os.path.expanduser("~/.keras/datasets/mnist.npz")
    if not force_digits and os.path.exists(cache):
        with np.load(cache) as d:
            x, y = d["x_train"], d["y_train"]
        x = x.reshape(len(x), -1).astype(np.float32)
        return "mnist", x, y.astype(np.int32), 255.0, (28, 28, 1)
    from sklearn.datasets import load_digits

    d = load_digits()
    return ("digits", d.data.astype(np.float32), d.target.astype(np.int32),
            16.0, (8, 8, 1))


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--workers", type=int, default=None)
    parser.add_argument("--epochs", type=int, default=5)
    parser.add_argument("--batch-size", type=int, default=32)
    parser.add_argument("--dispatch-epochs", type=int, default=1,
                        help="epochs per device dispatch (>1: one jitted "
                             "multi-epoch program with on-device reshuffle)")
    parser.add_argument("--digits", action="store_true",
                        help="pin the sklearn digits fallback regardless of "
                             "any cached MNIST (machine-independent runs)")
    args = parser.parse_args()

    import jax

    import distkeras_tpu as dk
    from distkeras_tpu.models import MLP, FlaxModel

    num_workers = args.workers or jax.device_count()
    _, x, y, max_val, img_shape = load_dataset(force_digits=args.digits)
    num_features = x.shape[1]
    print(f"dataset: {len(x)} samples, {num_features} features, "
          f"{num_workers} workers on {jax.default_backend()}")

    # 1. Raw data -> DataFrame (the reference reads a CSV into Spark here).
    df = dk.from_numpy(x, y, features_col="features_raw", label_col="label")

    # 2. Feature engineering with transformers (reference: MinMax + OneHot).
    df = dk.MinMaxTransformer(0.0, 1.0, 0.0, max_val,
                              input_col="features_raw",
                              output_col="features").transform(df)
    df = dk.OneHotTransformer(10, input_col="label",
                              output_col="label_encoded").transform(df)
    train_df, test_df = df.split(0.8, seed=0)
    print(f"train/test: {len(train_df)}/{len(test_df)}")

    def fresh_model():
        return FlaxModel(MLP(features=(256, 128), num_classes=10))

    def evaluate(trained) -> float:
        pred = dk.ModelPredictor(trained, features_col="features").predict(test_df)
        pred = dk.LabelIndexTransformer(10, input_col="prediction",
                                        output_col="prediction_index").transform(pred)
        return dk.AccuracyEvaluator(prediction_col="prediction_index",
                                    label_col="label").evaluate(pred)

    results = {}

    # 3. Baseline: SingleTrainer (reference experiment table row 1).
    trainer = dk.SingleTrainer(fresh_model(), loss="categorical_crossentropy",
                               worker_optimizer=("sgd", {"learning_rate": 0.1}),
                               features_col="features", label_col="label_encoded",
                               batch_size=args.batch_size, num_epoch=args.epochs,
                               dispatch_epochs=args.dispatch_epochs)
    results["SingleTrainer"] = (evaluate(trainer.train(train_df)),
                                trainer.get_training_time())

    # 4. Async data-parallel trainers.  The LR *scaling rules* follow
    # examples/experiments.py (the floor-enforced README table); windows
    # here keep this example's own shorter settings.  DOWNPOUR's commit
    # adds the SUM of per-worker window deltas, so its worker lr divides
    # by the worker count to keep the center step at the base lr; ADAG
    # pre-normalises each commit by the window, so its lr scales by
    # window/num_workers instead.  AEASGD's elastic pull is self-limiting.
    adag_window = 8
    for name, cls, kw in [
        ("DOWNPOUR", dk.DOWNPOUR,
         {"worker_optimizer": ("adam", {"learning_rate": 1e-3 / num_workers}),
          "communication_window": 5}),
        ("AEASGD", dk.AEASGD,
         {"worker_optimizer": ("sgd", {"learning_rate": 0.1}),
          "communication_window": 16, "rho": 1.0, "learning_rate": 0.05}),
        ("ADAG", dk.ADAG,
         {"worker_optimizer": ("adam",
                               {"learning_rate": 1e-3 * adag_window / num_workers}),
          "communication_window": adag_window}),
    ]:
        trainer = cls(fresh_model(), loss="categorical_crossentropy",
                      features_col="features", label_col="label_encoded",
                      num_workers=num_workers, batch_size=args.batch_size,
                      num_epoch=args.epochs,
                      dispatch_epochs=args.dispatch_epochs, **kw)
        acc = evaluate(trainer.train(train_df))
        results[name] = (acc, trainer.get_training_time())
        print(f"  {name}: parameter-server updates = {trainer.num_updates}")

    print(f"\n{'trainer':<16} {'accuracy':>9} {'time (s)':>9}")
    for name, (acc, t) in results.items():
        print(f"{name:<16} {acc:>9.4f} {t:>9.2f}")


if __name__ == "__main__":
    main()
