"""Per-trainer accuracy/time experiment table — the rebuild's equivalent of
the reference README's MNIST experiments section (SURVEY.md §6).

Runs the full DataFrame pipeline (transformers -> trainer -> predictor ->
evaluator) for SingleTrainer and all five async algorithms at their
reference-default communication windows, and prints a markdown table.  The
measured copy of this table lives in README.md; a floor-asserting regression
version runs as tests/test_experiment_table.py.

Run:  python examples/experiments.py [--workers N] [--epochs E] [--markdown]
      (add --cpu 8 to run on a faked 8-device CPU mesh, no TPU needed)

Dataset: ``keras.datasets.mnist`` when cached locally, else scikit-learn's
bundled 8x8 digits (offline-friendly, same pipeline).
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np


sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from mnist import load_dataset  # noqa: E402 — shared cached-MNIST/digits loader


def run_experiments(num_workers=None, epochs=10, batch_size=32, seed=0,
                    force_digits=False):
    """Train every trainer family on the same split; returns
    ``(dataset_name, {trainer: (accuracy, seconds)})``.  ``force_digits``
    pins the offline dataset so results don't depend on a cached MNIST."""
    import jax

    import distkeras_tpu as dk
    from distkeras_tpu.models import MLP, FlaxModel

    num_workers = num_workers or jax.device_count()
    name, x, y, max_val, _img_shape = load_dataset(force_digits=force_digits)

    df = dk.from_numpy(x, y, features_col="features_raw", label_col="label")
    df = dk.MinMaxTransformer(0.0, 1.0, 0.0, max_val,
                              input_col="features_raw",
                              output_col="features").transform(df)
    df = dk.OneHotTransformer(10, input_col="label",
                              output_col="label_encoded").transform(df)
    train_df, test_df = df.split(0.8, seed=0)

    def fresh_model():
        return FlaxModel(MLP(features=(256, 128), num_classes=10))

    def evaluate(trained) -> float:
        pred = dk.ModelPredictor(trained, features_col="features").predict(test_df)
        pred = dk.LabelIndexTransformer(10, input_col="prediction",
                                        output_col="prediction_index").transform(pred)
        return dk.AccuracyEvaluator(prediction_col="prediction_index",
                                    label_col="label").evaluate(pred)

    common = dict(loss="categorical_crossentropy",
                  features_col="features", label_col="label_encoded",
                  batch_size=batch_size, num_epoch=epochs, seed=seed)
    # Adaptive worker optimizer, matched across trainers: unnormalised
    # windowed-delta sums (DOWNPOUR/DynSGD) diverge under plain SGD as worker
    # count grows — the very instability ADAG's window normalisation was
    # invented to fix (arXiv:1710.02368) — and the reference's own mnist
    # example reached for adagrad for the same reason.
    adam = ("adam", {"learning_rate": 1e-3})
    # DOWNPOUR/DynSGD apply center += SUM of per-worker window deltas, so the
    # center's effective step grows ~linearly with worker count; dividing the
    # worker LR by N restores the single-worker effective step at the center
    # (measured on digits @8 workers: 0.885 -> 0.948, within ~1.6 points of
    # SingleTrainer — the tuning the reference's competitive 10-20-worker
    # tables imply).  ADAG normalises by the window instead; AEASGD/EAMSGD
    # commit elastic differences, not delta sums — neither needs the scaling.
    adam_sum = ("adam", {"learning_rate": 1e-3 / num_workers})
    adag_window = 12  # reference default (SURVEY.md §2); also scales ADAG's LR
    results = {}

    trainer = dk.SingleTrainer(fresh_model(), worker_optimizer=adam, **common)
    results["SingleTrainer"] = (evaluate(trainer.train(train_df)),
                                trainer.get_training_time())

    # Reference-default communication windows (SURVEY.md §2 trainer configs).
    async_trainers = [
        ("DOWNPOUR", dk.DOWNPOUR, {"worker_optimizer": adam_sum, "communication_window": 5}),
        ("AEASGD", dk.AEASGD, {"worker_optimizer": adam, "communication_window": 32,
                               "rho": 1.0, "learning_rate": 0.05}),
        ("EAMSGD", dk.EAMSGD, {"communication_window": 32, "rho": 1.0,
                               "learning_rate": 0.05, "momentum": 0.9}),
        # ADAG pre-normalises each commit by the window, so its center step is
        # (num_workers/window)x one worker step; lr * window/num_workers
        # restores the single-worker pace at any scale (= 1.5e-3 at 8 workers,
        # measured 0.942 -> 0.950 on digits).
        ("ADAG", dk.ADAG, {"worker_optimizer": ("adam", {"learning_rate": 1e-3 * adag_window / num_workers}),
                           "communication_window": adag_window}),
        ("DynSGD", dk.DynSGD, {"worker_optimizer": adam_sum, "communication_window": 5}),
    ]
    for trainer_name, cls, kw in async_trainers:
        trainer = cls(fresh_model(), num_workers=num_workers, **common, **kw)
        results[trainer_name] = (evaluate(trainer.train(train_df)),
                                 trainer.get_training_time())
    return name, results


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--workers", type=int, default=None)
    parser.add_argument("--epochs", type=int, default=10)
    parser.add_argument("--batch-size", type=int, default=32)
    parser.add_argument("--markdown", action="store_true")
    parser.add_argument("--cpu", type=int, default=0, metavar="N",
                        help="force an N-device CPU mesh (offline / no TPU)")
    args = parser.parse_args()

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", args.cpu)

    name, results = run_experiments(args.workers, args.epochs, args.batch_size)
    backend = jax.default_backend()
    n_dev = jax.device_count()
    print(f"\ndataset={name}, backend={backend} x{n_dev}, epochs={args.epochs}")
    if args.markdown:
        print("| trainer | accuracy | time (s) |")
        print("|---|---|---|")
        for trainer_name, (acc, t) in results.items():
            print(f"| {trainer_name} | {acc:.4f} | {t:.1f} |")
    else:
        print(f"{'trainer':<16} {'accuracy':>9} {'time (s)':>9}")
        for trainer_name, (acc, t) in results.items():
            print(f"{trainer_name:<16} {acc:>9.4f} {t:>9.2f}")


if __name__ == "__main__":
    main()
