"""Tour of the parallelism axes beyond plain data parallelism.

The reference's only axis was Spark-task data parallelism; this example runs
the rebuild's seven extra axes on a faked 8-device CPU mesh so it works on
any machine (swap to real chips by deleting the two config lines):

  1. virtual workers      — more logical workers than devices (the analogue
                            of the reference's ``parallelism_factor``)
  2. sequence parallelism — ring attention over a (workers x seq) mesh
  3. tensor parallelism   — GSPMD engine over a (workers x model) mesh
  4. staleness simulation — per-worker commit periods (deterministic
                            asynchrony), here combined with TP
  5. pipeline parallelism — microbatch ppermute pipeline over a
                            (workers x stages) mesh (staged transformer)
  6. expert parallelism   — Switch MoE with the expert stacks sharded over
                            the model axis (GSPMD placement override)
  7. FSDP / ZeRO-3        — the center variable sharded over the workers
                            axis (gather-at-use) instead of replicated
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax

if os.environ.get("DK_TPU") != "1":  # delete these two lines on real chips
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 8)

import numpy as np


def main():
    import distkeras_tpu as dk
    from distkeras_tpu.models import FlaxModel, MLP, TransformerClassifier

    print(f"devices: {jax.device_count()}")

    rng = np.random.default_rng(0)
    x = rng.normal(size=(2048, 16)).astype(np.float32)
    y = np.argmax(x @ rng.normal(size=(16, 4)), axis=1).astype(np.int32)
    df = dk.from_numpy(x, np.eye(4, dtype=np.float32)[y])

    def report(tag, trainer, trained, data=x, labels=y):
        preds = np.argmax(trained.predict(data), -1)
        acc = np.mean(preds == labels)
        print(f"{tag:28s} acc={acc:.3f} time={trainer.get_training_time():.1f}s")

    # 1. virtual workers: 16 logical workers on 8 devices
    t = dk.DOWNPOUR(FlaxModel(MLP(features=(64,), num_classes=4)),
                    worker_optimizer=("sgd", {"learning_rate": 0.1}),
                    num_workers=16, batch_size=16, num_epoch=5,
                    communication_window=4)
    report("16 virtual workers / 8 dev", t, t.train(df))

    # 2. sequence parallelism: transformer tokens sharded 2-way
    tokens = rng.integers(0, 64, size=(1024, 32)).astype(np.int32)
    ty = ((tokens == 7).sum(1) > (tokens == 3).sum(1)).astype(np.int32)
    tdf = dk.from_numpy(tokens, np.eye(2, dtype=np.float32)[ty])
    t = dk.DOWNPOUR(FlaxModel(TransformerClassifier(
                        vocab_size=64, num_classes=2, dim=32, heads=2,
                        num_layers=1, max_len=64, seq_axis="seq")),
                    worker_optimizer=("adam", {"learning_rate": 3e-3}),
                    num_workers=4, batch_size=16, num_epoch=10,
                    communication_window=2, seq_shards=2)
    report("ring attention 4w x 2seq", t, t.train(tdf), tokens, ty)

    # 3. tensor parallelism: same trainer API, GSPMD engine
    t = dk.DOWNPOUR(FlaxModel(MLP(features=(64,), num_classes=4)),
                    worker_optimizer=("sgd", {"learning_rate": 0.1}),
                    num_workers=4, batch_size=16, num_epoch=5,
                    communication_window=4, tp_shards=2)
    report("tensor parallel 4w x 2mp", t, t.train(df))

    # 4. deterministic asynchrony (per-worker commit periods) under TP
    t = dk.DynSGD(FlaxModel(MLP(features=(64,), num_classes=4)),
                  worker_optimizer=("sgd", {"learning_rate": 0.1}),
                  num_workers=4, batch_size=16, num_epoch=5,
                  communication_window=4, tp_shards=2,
                  commit_schedule=[3, 4, 5, 6])
    report("DynSGD staleness sim + TP", t, t.train(df))

    # 5. pipeline parallelism: staged transformer, 2 workers x 4 stages
    from distkeras_tpu.models import StagedTransformer

    t = dk.DOWNPOUR(StagedTransformer(vocab_size=64, num_classes=2, dim=32,
                                      heads=2, num_stages=4,
                                      blocks_per_stage=1, max_len=64),
                    worker_optimizer=("adam", {"learning_rate": 2e-3}),
                    num_workers=2, batch_size=16, num_epoch=10,
                    communication_window=2, pipeline_stages=4)
    report("pipeline 2w x 4 stages", t, t.train(tdf), tokens, ty)

    # 6. expert parallelism: Switch MoE, experts sharded over the model axis
    from distkeras_tpu.models import MoETransformerClassifier, expert_partition

    t = dk.DOWNPOUR(FlaxModel(MoETransformerClassifier(
                        vocab_size=64, num_classes=2, dim=32, heads=2,
                        num_layers=1, num_experts=4, mlp_ratio=2,
                        max_len=64)),
                    worker_optimizer=("adam", {"learning_rate": 2e-3}),
                    num_workers=4, batch_size=16, num_epoch=10,
                    communication_window=2, tp_shards=2,
                    tp_spec_fn=expert_partition(4))
    report("Switch MoE 4w x 2experts", t, t.train(tdf), tokens, ty)

    # 7. FSDP / ZeRO-3: the center variable itself sharded over the workers
    #    axis (all-gather at pull, reduce-scatter at commit) — same
    #    trajectory as plain DP, 1/num_devices the center HBM
    t = dk.DOWNPOUR(FlaxModel(MLP(features=(64,), num_classes=4)),
                    worker_optimizer=("sgd", {"learning_rate": 0.1}),
                    num_workers=8, batch_size=16, num_epoch=5,
                    communication_window=4, fsdp=True)
    report("FSDP-sharded center 8w", t, t.train(df))


if __name__ == "__main__":
    main()
